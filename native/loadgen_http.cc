// Closed-loop keep-alive HTTP load generator (the reference drives its
// benchmark with a distributed locust fleet, util/loadtester/scripts/
// predict_rest_locust.py:17-53; on a single host the equivalent pressure
// needs a compiled client — Python asyncio cannot generate >10k rps/core).
//
// N connections, each with exactly one request in flight (locust-style
// closed loop). Reports throughput + latency percentiles as one JSON line.

#include <algorithm>
#include <cerrno>
#include <cinttypes>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <string_view>
#include <vector>

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>
#include <ctime>

namespace {

uint64_t now_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + ts.tv_nsec;
}

struct Conn {
  int fd = -1;
  std::string inbuf;
  size_t sent = 0;
  uint64_t t_send = 0;
  bool in_flight = false;
};

struct Stats {
  std::vector<uint32_t> lat_us;
  uint64_t ok = 0, errors = 0, shed = 0, bytes = 0;
};

int connect_nonblock(const char* host, int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  if (getaddrinfo(host, nullptr, &hints, &res) != 0 || !res) {
    fprintf(stderr, "cannot resolve host %s\n", host);
    close(fd);
    return -1;
  }
  addr.sin_addr = ((sockaddr_in*)res->ai_addr)->sin_addr;
  freeaddrinfo(res);
  if (connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    close(fd);
    return -1;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  int flags = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, flags | O_NONBLOCK);
  return fd;
}

}  // namespace

int main(int argc, char** argv) {
  const char* host = "127.0.0.1";
  int port = 8000;
  const char* path = "/api/v0.1/predictions";
  std::string body = "{\"data\": {\"ndarray\": [[1.0, 2.0, 3.0, 4.0]]}}";
  int connections = 32;
  double duration_s = 10.0, warmup_s = 1.0;
  const char* label = "rest";
  for (int i = 1; i < argc; ++i) {
    std::string_view a = argv[i];
    auto next = [&]() -> const char* { return i + 1 < argc ? argv[++i] : ""; };
    if (a == "--host") host = next();
    else if (a == "--port") port = atoi(next());
    else if (a == "--path") path = next();
    else if (a == "--body") body = next();
    else if (a == "--body-file") {
      FILE* f = fopen(next(), "rb");
      if (!f) { perror("body-file"); return 2; }
      body.clear();
      char tmp[4096];
      size_t n;
      while ((n = fread(tmp, 1, sizeof(tmp), f)) > 0) body.append(tmp, n);
      fclose(f);
    } else if (a == "--connections") connections = atoi(next());
    else if (a == "--duration") duration_s = atof(next());
    else if (a == "--warmup") warmup_s = atof(next());
    else if (a == "--label") label = next();
    else { fprintf(stderr, "unknown arg %s\n", argv[i]); return 2; }
  }
  signal(SIGPIPE, SIG_IGN);

  char reqbuf[65536];
  int reqlen = snprintf(reqbuf, sizeof(reqbuf),
                        "POST %s HTTP/1.1\r\nHost: %s:%d\r\nContent-Type: "
                        "application/json\r\nContent-Length: %zu\r\n\r\n%s",
                        path, host, port, body.size(), body.c_str());
  if (reqlen <= 0 || reqlen >= (int)sizeof(reqbuf)) {
    fprintf(stderr, "request too large\n");
    return 2;
  }

  std::vector<Conn> conns(connections);
  int epfd = epoll_create1(0);
  for (int i = 0; i < connections; ++i) {
    conns[i].fd = connect_nonblock(host, port);
    if (conns[i].fd < 0) {
      fprintf(stderr, "connect failed\n");
      return 1;
    }
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u32 = (uint32_t)i;
    epoll_ctl(epfd, EPOLL_CTL_ADD, conns[i].fd, &ev);
  }

  Stats stats;
  stats.lat_us.reserve(1 << 20);
  uint64_t t_start = now_ns();
  uint64_t t_measure = t_start + (uint64_t)(warmup_s * 1e9);
  uint64_t t_end = t_measure + (uint64_t)(duration_s * 1e9);
  bool measuring = warmup_s <= 0;

  auto send_req = [&](Conn& c) {
    c.t_send = now_ns();
    c.in_flight = true;
    ssize_t n = ::send(c.fd, reqbuf, reqlen, MSG_NOSIGNAL);
    (void)n;  // closed loop on loopback: the request fits the socket buffer
  };
  for (auto& c : conns) send_req(c);

  std::vector<epoll_event> events(256);
  char rbuf[65536];
  for (;;) {
    uint64_t now = now_ns();
    if (now >= t_end) break;
    if (!measuring && now >= t_measure) {
      measuring = true;
      stats.ok = stats.errors = stats.shed = stats.bytes = 0;
      stats.lat_us.clear();
    }
    int n = epoll_wait(epfd, events.data(), (int)events.size(), 100);
    for (int i = 0; i < n; ++i) {
      Conn& c = conns[events[i].data.u32];
      ssize_t got = ::recv(c.fd, rbuf, sizeof(rbuf), 0);
      if (got <= 0) {
        if (got < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) continue;
        fprintf(stderr, "connection lost\n");
        return 1;
      }
      c.inbuf.append(rbuf, (size_t)got);
      // complete response? headers + content-length body
      for (;;) {
        size_t hdr_end = c.inbuf.find("\r\n\r\n");
        if (hdr_end == std::string::npos) break;
        size_t clpos = c.inbuf.find("Content-Length:");
        if (clpos == std::string::npos || clpos > hdr_end) break;
        size_t content_len = strtoul(c.inbuf.c_str() + clpos + 15, nullptr, 10);
        size_t total = hdr_end + 4 + content_len;
        if (c.inbuf.size() < total) break;
        bool ok = c.inbuf.compare(0, 12, "HTTP/1.1 200") == 0;
        // deterministic overload shed (well-formed, by design) is its own
        // bucket: an assertion of zero FAILURES must still hold past the knee
        bool is_shed = !ok && c.inbuf.compare(0, 12, "HTTP/1.1 429") == 0;
        uint64_t lat = now_ns() - c.t_send;
        if (measuring) {
          if (ok) ++stats.ok;
          else if (is_shed) ++stats.shed;
          else ++stats.errors;
          stats.bytes += total;
          // percentiles describe SERVED requests; near-instant sheds would
          // otherwise dominate the distribution under overload
          if (ok) stats.lat_us.push_back((uint32_t)(lat / 1000));
        }
        c.inbuf.erase(0, total);
        c.in_flight = false;
        send_req(c);
      }
    }
  }
  double elapsed = 1e-9 * (now_ns() - t_measure);
  std::sort(stats.lat_us.begin(), stats.lat_us.end());
  auto pct = [&](double p) -> double {
    if (stats.lat_us.empty()) return 0;
    size_t idx = (size_t)(p / 100.0 * stats.lat_us.size());
    if (idx >= stats.lat_us.size()) idx = stats.lat_us.size() - 1;
    return stats.lat_us[idx] / 1000.0;  // ms
  };
  double mean = 0;
  for (auto v : stats.lat_us) mean += v;
  mean = stats.lat_us.empty() ? 0 : mean / stats.lat_us.size() / 1000.0;
  printf("{\"label\": \"%s\", \"throughput_rps\": %.2f, \"requests\": %" PRIu64
         ", \"failures\": %" PRIu64 ", \"shed\": %" PRIu64
         ", \"duration_s\": %.2f, \"connections\": %d, \"latency_ms\": "
         "{\"mean\": %.3f, \"p50\": %.3f, \"p75\": %.3f, \"p90\": %.3f, "
         "\"p95\": %.3f, \"p98\": %.3f, \"p99\": %.3f, \"max\": %.3f}}\n",
         label, stats.ok / elapsed, stats.ok, stats.errors, stats.shed,
         elapsed, connections, mean, pct(50), pct(75), pct(90), pct(95),
         pct(98), pct(99),
         stats.lat_us.empty() ? 0 : stats.lat_us.back() / 1000.0);
  return stats.errors == 0 ? 0 : 3;
}
