"""Extract + verify the ziggurat tables numpy's Generator uses, emit
``native/ziggurat_tables.h``.

Seeded Thompson routing replays ``numpy.random.default_rng(seed).beta``
draw-for-draw on the native edge (analytics/routers.py
``ThompsonSampling.route`` -> np_rng.h).  ``beta`` consumes
``standard_gamma`` which consumes the ziggurat ``standard_normal`` /
``standard_exponential`` samplers, and those compare raw 52/53-bit draws
against precomputed acceptance tables — replay is bit-exact only with the
IDENTICAL tables.  The tables are deterministic constants of the published
ziggurat(256) construction (Marsaglia & Tsang 2000, as instantiated by
numpy's ziggurat_constants.h); rather than re-deriving them and risking
ULP drift, this script reads them out of the *installed* numpy binary
(the exact library the Python plane draws from), PROVES them by replaying
numpy's samplers in pure Python over ``PCG64.random_raw`` streams against
``Generator`` outputs across seeds/shapes/paths, and only then writes the
header.  Re-run after a numpy upgrade; tests/test_native.py re-proves the
C side against numpy on every run.

Usage: python native/gen_ziggurat_tables.py [--check-only]
"""

from __future__ import annotations

import math
import os
import struct
import sys

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(HERE, "ziggurat_tables.h")

TWO53_INV = 1.0 / 9007199254740992.0


# ---------------------------------------------------------------------------
# 1. locate the tables in the installed numpy _generator extension
# ---------------------------------------------------------------------------

def _find_tables() -> dict:
    import numpy.random._generator as gmod

    data = open(gmod.__file__, "rb").read()

    def doubles(off, n=256):
        return struct.unpack_from("<%dd" % n, data, off)

    def u64s(off, n=256):
        return struct.unpack_from("<%dQ" % n, data, off)

    # Anchors: the f-tables start at exactly 1.0 and decrease to
    # f(r) = exp(-r) (exponential) / exp(-r^2/2) (normal).
    fe_off = fi_off = None
    one = struct.pack("<d", 1.0)
    i = data.find(one)
    while i != -1 and (fe_off is None or fi_off is None):
        if i % 8 == 0:
            arr = doubles(i)
            if all(0.0 < x <= 1.0 for x in arr) and all(
                arr[j] > arr[j + 1] for j in range(255)
            ):
                last = arr[255]
                if abs(last - math.exp(-7.697117470131487)) < 1e-9:
                    fe_off = i
                elif abs(last - math.exp(-0.5 * 3.6541528853610088**2)) < 1e-9:
                    fi_off = i
        i = data.find(one, i + 1)
    if fe_off is None or fi_off is None:
        raise RuntimeError("could not locate fe/fi ziggurat tables in numpy")

    def locate_w_k(f_off, q_value, frac_bits):
        """w/k tables live adjacent to their f table: w[0] = q / 2^bits,
        k[1] = 0 with k[0] ~ (r/q) * 2^bits."""
        w_off = k_off = None
        for off in range(max(0, f_off - 16 * 2048), len(data) - 2048, 8):
            first = struct.unpack_from("<d", data, off)[0]
            target = q_value / (1 << frac_bits)
            if w_off is None and abs(first - target) < 1e-6 * target:
                arr = doubles(off)
                if all(0.0 < x < 1e-14 for x in arr):
                    w_off = off
            k = u64s(off, 3)
            if k_off is None and k[1] == 0 and 0 < k[0] < (1 << frac_bits):
                arr = u64s(off)
                if all(x < (1 << frac_bits) for x in arr) and arr[0] > (
                    (1 << frac_bits) * 8
                ) // 10:
                    k_off = off
            if w_off is not None and k_off is not None:
                return doubles(w_off), u64s(k_off)
        raise RuntimeError("could not locate w/k ziggurat tables in numpy")

    # q = v / f(r): base-strip width
    fe = doubles(fe_off)
    fi = doubles(fi_off)
    # derive q from the known v constants of the published construction
    q_exp = 0.0039496598225815571993 / fe[255]
    q_nor = 0.00492867323399 / fi[255]
    we, ke = locate_w_k(fe_off, q_exp, 53)
    wi, ki = locate_w_k(fi_off, q_nor, 52)

    # the tail constants as the exact doubles the binary carries (the
    # compiled code stores -inv_r; literals can differ from computed
    # 1/r in the last ulp, so take everything from the binary)
    def find_double_near(value):
        lo = min(value * (1 - 1e-9), value * (1 + 1e-9))
        hi = max(value * (1 - 1e-9), value * (1 + 1e-9))
        for off in range(0, len(data) - 8, 8):
            v = struct.unpack_from("<d", data, off)[0]
            if lo <= v <= hi:
                return v
        raise RuntimeError(f"constant near {value} not found")

    nor_r = find_double_near(3.6541528853610088)
    nor_inv_r = -find_double_near(-1.0 / 3.6541528853610088)
    exp_r = find_double_near(7.697117470131487)
    return {
        "fe": fe, "we": we, "ke": ke,
        "fi": fi, "wi": wi, "ki": ki,
        "nor_r": nor_r, "nor_inv_r": nor_inv_r, "exp_r": exp_r,
    }


# ---------------------------------------------------------------------------
# 2. pure-Python replay of numpy's samplers over a raw PCG64 stream
# ---------------------------------------------------------------------------

class Stream:
    """Raw uint64 draws from PCG64(seed) — the exact stream Generator
    consumes (next_double/next_uint64 never touch the uint32 buffer)."""

    def __init__(self, seed, n=1 << 20):
        self.vals = np.random.PCG64(seed).random_raw(n).tolist()
        self.i = 0

    def u64(self):
        v = self.vals[self.i]
        self.i += 1
        return v

    def dbl(self):
        return (self.u64() >> 11) * TWO53_INV


def sim_normal(s: Stream, T: dict) -> float:
    while True:
        r = s.u64()
        idx = r & 0xFF
        r >>= 8
        sign = r & 0x1
        rabs = (r >> 1) & 0x000FFFFFFFFFFFFF
        x = rabs * T["wi"][idx]
        if sign:
            x = -x
        if rabs < T["ki"][idx]:
            return x
        if idx == 0:
            while True:
                xx = -T["nor_inv_r"] * math.log1p(-s.dbl())
                yy = -math.log1p(-s.dbl())
                if yy + yy > xx * xx:
                    return (
                        -(T["nor_r"] + xx)
                        if (rabs >> 8) & 0x1
                        else T["nor_r"] + xx
                    )
        else:
            if (T["fi"][idx - 1] - T["fi"][idx]) * s.dbl() + T["fi"][
                idx
            ] < math.exp(-0.5 * x * x):
                return x


def sim_exponential(s: Stream, T: dict) -> float:
    while True:
        ri = s.u64()
        ri >>= 3
        idx = ri & 0xFF
        ri >>= 8
        x = ri * T["we"][idx]
        if ri < T["ke"][idx]:
            return x
        if idx == 0:
            return T["exp_r"] - math.log1p(-s.dbl())
        if (T["fe"][idx - 1] - T["fe"][idx]) * s.dbl() + T["fe"][
            idx
        ] < math.exp(-x):
            return x


def sim_standard_gamma(s: Stream, T: dict, shape: float) -> float:
    if shape == 1.0:
        return sim_exponential(s, T)
    if shape == 0.0:
        return 0.0
    if shape < 1.0:
        while True:
            U = s.dbl()
            V = sim_exponential(s, T)
            if U <= 1.0 - shape:
                X = U ** (1.0 / shape)
                if X <= V:
                    return X
            else:
                Y = -math.log((1.0 - U) / shape)
                X = (1.0 - shape + shape * Y) ** (1.0 / shape)
                if X <= V + Y:
                    return X
    b = shape - 1.0 / 3.0
    c = 1.0 / math.sqrt(9.0 * b)
    while True:
        while True:
            X = sim_normal(s, T)
            V = 1.0 + c * X
            if V > 0.0:
                break
        V = V * V * V
        U = s.dbl()
        if U < 1.0 - 0.0331 * (X * X) * (X * X):
            return b * V
        # numpy computes a bare log(U): U==0 gives -inf, which compares True
        # against the finite rhs — numpy ACCEPTS and returns b*V. Mirror that
        # exactly (math.log(0) would raise, so map it to -inf explicitly).
        logU = math.log(U) if U > 0.0 else -math.inf
        if logU < 0.5 * X * X + b * (1.0 - V + math.log(V)):
            return b * V


def sim_beta(s: Stream, T: dict, a: float, b: float) -> float:
    if a <= 1.0 and b <= 1.0:
        while True:
            U = s.dbl()
            V = s.dbl()
            X = U ** (1.0 / a)
            Y = V ** (1.0 / b)
            XpY = X + Y
            if XpY <= 1.0 and U + V > 0.0:
                if XpY > 0:
                    return X / XpY
                logX = math.log(U) / a
                logY = math.log(V) / b
                logM = max(logX, logY)
                logX -= logM
                logY -= logM
                return math.exp(
                    logX - math.log(math.exp(logX) + math.exp(logY))
                )
    Ga = sim_standard_gamma(s, T, a)
    Gb = sim_standard_gamma(s, T, b)
    return Ga / (Ga + Gb)


# ---------------------------------------------------------------------------
# 3. proof: replay vs numpy across seeds, shapes, and every code path
# ---------------------------------------------------------------------------

def verify(T: dict) -> None:
    n = 4000
    for seed in range(8):
        g = np.random.Generator(np.random.PCG64(seed))
        want = g.standard_normal(n)
        s = Stream(seed)
        got = [sim_normal(s, T) for _ in range(n)]
        assert all(w == v for w, v in zip(want, got)), f"normal seed={seed}"

        g = np.random.Generator(np.random.PCG64(seed))
        want = g.standard_exponential(n)
        s = Stream(seed)
        got = [sim_exponential(s, T) for _ in range(n)]
        assert all(w == v for w, v in zip(want, got)), f"expon seed={seed}"

    shapes = [0.05, 0.3, 0.9999, 1.0, 1.0001, 4.0 / 3.0, 2.5, 17.0, 500.0]
    for seed in range(4):
        for shape in shapes:
            g = np.random.Generator(np.random.PCG64(seed))
            want = g.standard_gamma(shape, size=800)
            s = Stream(seed)
            got = [sim_standard_gamma(s, T, shape) for _ in range(800)]
            assert all(w == v for w, v in zip(want, got)), (
                f"gamma shape={shape} seed={seed}")

    # (0.001, 0.001) drives the pow-underflow log-space Johnk branch on
    # ~24% of draws; (0.005, 0.005) mixes it with the ratio branch
    pairs = [(1.0, 1.0), (0.5, 0.5), (0.3, 0.9), (1.0, 2.0), (2.0, 1.0),
             (1.5, 3.25), (30.0, 2.0), (1.0, 1.5), (0.5, 2.0),
             (0.001, 0.001), (0.005, 0.005)]
    for seed in range(4):
        for a, b in pairs:
            g = np.random.Generator(np.random.PCG64(seed))
            want = g.beta(a, b, size=500)
            s = Stream(seed)
            got = [sim_beta(s, T, a, b) for _ in range(500)]
            assert all(w == v for w, v in zip(want, got)), (
                f"beta a={a} b={b} seed={seed}")

    # the Thompson shape: array draws interleave elementwise in C order
    for seed in range(4):
        g = np.random.Generator(np.random.PCG64(seed))
        a = np.array([1.0, 3.5, 1.0, 0.7])
        b = np.array([2.0, 1.0, 1.0, 0.7])
        want = np.stack([g.beta(a, b) for _ in range(200)])
        s = Stream(seed)
        got = np.stack([
            np.array([sim_beta(s, T, ai, bi) for ai, bi in zip(a, b)])
            for _ in range(200)
        ])
        assert (want == got).all(), f"beta-array seed={seed}"
    print("verified: normal/exponential/gamma/beta replay numpy %s "
          "draw-for-draw" % np.__version__)


# ---------------------------------------------------------------------------
# 4. emit
# ---------------------------------------------------------------------------

def emit(T: dict) -> None:
    def dbl(v):
        return repr(struct.unpack("<d", struct.pack("<d", v))[0])

    lines = [
        "// Ziggurat acceptance tables for the numpy-replay samplers in",
        "// np_rng.h — deterministic constants of the published",
        "// ziggurat(256) construction as instantiated by numpy "
        + np.__version__ + ",",
        "// extracted from the installed library and PROVEN draw-for-draw",
        "// by native/gen_ziggurat_tables.py (re-run it after a numpy",
        "// upgrade).  Do not edit by hand.",
        "#pragma once",
        "#include <cstdint>",
        "",
        "namespace nprng {",
        "",
        f"inline constexpr double kZigNorR = {dbl(T['nor_r'])};",
        f"inline constexpr double kZigNorInvR = {dbl(T['nor_inv_r'])};",
        f"inline constexpr double kZigExpR = {dbl(T['exp_r'])};",
        "",
    ]

    def table(name, vals, fmt):
        ctype = "uint64_t" if fmt == "u" else "double"
        lines.append(f"inline constexpr {ctype} {name}[256] = {{")
        row = []
        for v in vals:
            row.append(("0x%016xull" % v) if fmt == "u" else dbl(v))
            if len(row) == 4:
                lines.append("    " + ", ".join(row) + ",")
                row = []
        if row:
            lines.append("    " + ", ".join(row) + ",")
        lines.append("};")
        lines.append("")

    table("kZigKi", T["ki"], "u")
    table("kZigWi", T["wi"], "d")
    table("kZigFi", T["fi"], "d")
    table("kZigKe", T["ke"], "u")
    table("kZigWe", T["we"], "d")
    table("kZigFe", T["fe"], "d")
    lines.append("}  // namespace nprng")
    lines.append("")
    with open(OUT, "w") as f:
        f.write("\n".join(lines))
    print("wrote", OUT)


if __name__ == "__main__":
    tables = _find_tables()
    verify(tables)
    if "--check-only" not in sys.argv:
        emit(tables)
