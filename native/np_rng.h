// Bit-exact replays of the two RNG streams the Python routers draw from,
// so SEEDED router units can execute on the native edge and still reproduce
// the Python engine's routing decisions request-for-request:
//
//   NpRng  — numpy ``np.random.default_rng(seed)``: SeedSequence -> PCG64
//            (setseq 128/64 XSL-RR) with the Generator's buffered uint32
//            path and Lemire bounded integers. Used by the bandit routers
//            (analytics/routers.py `_BanditRouter.__init__`).
//   PyRng  — CPython ``random.Random(seed)``: MT19937 via init_by_array,
//            53-bit random(), _randbelow via getrandbits rejection. Used by
//            RandomABTest (components/builtin.py).
//
// Parity is enforced by tests/test_native.py::test_np_rng_parity* which
// compare these (via ctypes hooks in ring.cc) against numpy / CPython
// draw-for-draw, including the uint32-buffer interleaving.

#pragma once

#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

#include "ziggurat_tables.h"

namespace nprng {

using uint128 = unsigned __int128;

// ---------------------------------------------------------------------------
// numpy SeedSequence (pool_size 4, uint32 words) — bit_generator.pyx
// ---------------------------------------------------------------------------
struct SeedSequence {
  static constexpr uint32_t INIT_A = 0x43b0d7e5u, MULT_A = 0x931e8875u;
  static constexpr uint32_t INIT_B = 0x8b51f9ddu, MULT_B = 0x58f38dedu;
  static constexpr uint32_t MIX_MULT_L = 0xca01f9ddu, MIX_MULT_R = 0x4973f715u;
  static constexpr int XSHIFT = 16, POOL_SIZE = 4;

  uint32_t pool[POOL_SIZE];

  explicit SeedSequence(uint64_t seed) {
    // entropy = the seed as little-endian uint32 words (numpy
    // _coerce_to_uint32_array; 0 stays one zero word)
    std::vector<uint32_t> entropy;
    if (seed == 0) {
      entropy.push_back(0);
    } else {
      while (seed) {
        entropy.push_back(static_cast<uint32_t>(seed));
        seed >>= 32;
      }
    }
    uint32_t hash_const = INIT_A;
    auto hash = [&hash_const](uint32_t value) {
      value ^= hash_const;
      hash_const *= MULT_A;
      value *= hash_const;
      value ^= value >> XSHIFT;
      return value;
    };
    auto mix = [](uint32_t x, uint32_t y) {
      uint32_t result = x * MIX_MULT_L - y * MIX_MULT_R;
      result ^= result >> XSHIFT;
      return result;
    };
    for (int i = 0; i < POOL_SIZE; ++i)
      pool[i] = hash(i < (int)entropy.size() ? entropy[i] : 0);
    for (int i_src = 0; i_src < POOL_SIZE; ++i_src)
      for (int i_dst = 0; i_dst < POOL_SIZE; ++i_dst)
        if (i_src != i_dst) pool[i_dst] = mix(pool[i_dst], hash(pool[i_src]));
    for (int i_src = POOL_SIZE; i_src < (int)entropy.size(); ++i_src)
      for (int i_dst = 0; i_dst < POOL_SIZE; ++i_dst)
        pool[i_dst] = mix(pool[i_dst], hash(entropy[i_src]));
  }

  // n 32-bit words of generated state
  void generate_state(uint32_t* out, int n) const {
    uint32_t hash_const = INIT_B;
    for (int i = 0; i < n; ++i) {
      uint32_t v = pool[i % POOL_SIZE];
      v ^= hash_const;
      hash_const *= MULT_B;
      v *= hash_const;
      v ^= v >> XSHIFT;
      out[i] = v;
    }
  }
};

// ---------------------------------------------------------------------------
// PCG64 (setseq 128/64 XSL-RR) + numpy Generator draw protocols
// ---------------------------------------------------------------------------
struct NpRng {
  uint128 state = 0, inc = 0;
  // numpy's pcg64_next32 buffers the high half of a 64-bit draw
  bool has_uint32 = false;
  uint32_t uinteger = 0;

  static constexpr uint64_t MUL_HI = 0x2360ed051fc65da4ull;
  static constexpr uint64_t MUL_LO = 0x4385df649fccf645ull;

  explicit NpRng(uint64_t seed) {
    SeedSequence ss(seed);
    uint32_t w[8];
    ss.generate_state(w, 8);  // = generate_state(4, uint64) little-endian
    auto u64 = [&w](int i) {
      return (uint64_t)w[2 * i] | ((uint64_t)w[2 * i + 1] << 32);
    };
    // pcg64_set_seed: seed words 0..1 (hi, lo), inc words 2..3 (hi, lo)
    uint128 initstate = ((uint128)u64(0) << 64) | u64(1);
    uint128 initseq = ((uint128)u64(2) << 64) | u64(3);
    state = 0;
    inc = (initseq << 1) | 1;
    step();
    state += initstate;
    step();
  }

  void step() {
    const uint128 mul = ((uint128)MUL_HI << 64) | MUL_LO;
    state = state * mul + inc;
  }

  uint64_t next64() {
    step();
    uint64_t hi = (uint64_t)(state >> 64), lo = (uint64_t)state;
    uint64_t value = hi ^ lo;
    unsigned rot = (unsigned)(state >> 122);
    return rot ? (value >> rot) | (value << (64 - rot)) : value;
  }

  uint32_t next32() {
    if (has_uint32) {
      has_uint32 = false;
      return uinteger;
    }
    uint64_t v = next64();
    has_uint32 = true;
    uinteger = (uint32_t)(v >> 32);
    return (uint32_t)v;
  }

  // Generator.random(): 53-bit double in [0, 1)
  double random() { return (next64() >> 11) * (1.0 / 9007199254740992.0); }

  // Generator.integers(0, n) for int64 dtype, 0 < n <= 2^32: numpy's
  // random_bounded_uint64_fill takes the 32-bit path (rng = n-1 fits in
  // uint32) -> buffered Lemire over next32 (distributions.c
  // buffered_bounded_lemire_uint32).
  uint64_t integers(uint64_t n) {
    uint64_t rng = n - 1;
    if (rng == 0) return 0;
    if (rng == 0xFFFFFFFFull) return next32();
    uint32_t rng_excl = (uint32_t)(rng + 1);
    uint64_t m = (uint64_t)next32() * rng_excl;
    uint32_t leftover = (uint32_t)m;
    if (leftover < rng_excl) {
      const uint32_t threshold = (uint32_t)(-rng_excl) % rng_excl;  // 2^32 % excl
      while (leftover < threshold) {
        m = (uint64_t)next32() * rng_excl;
        leftover = (uint32_t)m;
      }
    }
    return m >> 32;
  }

  // --- distributions.c replays (exact draw-for-draw): the ziggurat
  // samplers + Marsaglia-Tsang gamma + Johnk/two-gamma beta Thompson
  // routing consumes via Generator.beta.  Tables in ziggurat_tables.h are
  // extracted from the installed numpy and proven by
  // native/gen_ziggurat_tables.py; the C side is re-proven against numpy
  // by tests/test_native.py::test_np_rng_gamma_beta_parity. ---

  // random_standard_normal: 256-strip ziggurat over a 52-bit mantissa
  double standard_normal() {
    for (;;) {
      uint64_t r = next64();
      int idx = (int)(r & 0xff);
      r >>= 8;
      int sign = (int)(r & 0x1);
      uint64_t rabs = (r >> 1) & 0x000fffffffffffffull;
      double x = (double)rabs * kZigWi[idx];
      if (sign) x = -x;
      if (rabs < kZigKi[idx]) return x;
      if (idx == 0) {
        for (;;) {
          double xx = -kZigNorInvR * log1p(-random());
          double yy = -log1p(-random());
          if (yy + yy > xx * xx)
            return ((rabs >> 8) & 0x1) ? -(kZigNorR + xx) : kZigNorR + xx;
        }
      } else {
        if ((kZigFi[idx - 1] - kZigFi[idx]) * random() + kZigFi[idx] <
            exp(-0.5 * x * x))
          return x;
      }
    }
  }

  // random_standard_exponential: ziggurat over a 53-bit mantissa
  double standard_exponential() {
    for (;;) {
      uint64_t ri = next64();
      ri >>= 3;
      int idx = (int)(ri & 0xff);
      ri >>= 8;
      double x = (double)ri * kZigWe[idx];
      if (ri < kZigKe[idx]) return x;
      if (idx == 0) return kZigExpR - log1p(-random());
      if ((kZigFe[idx - 1] - kZigFe[idx]) * random() + kZigFe[idx] <
          exp(-x))
        return x;
    }
  }

  // random_standard_gamma: exponential at shape 1, Best/Ahrens-Dieter-
  // style boost below 1, Marsaglia-Tsang squeeze above
  double standard_gamma(double shape) {
    if (shape == 1.0) return standard_exponential();
    if (shape == 0.0) return 0.0;
    if (shape < 1.0) {
      for (;;) {
        double U = random();
        double V = standard_exponential();
        if (U <= 1.0 - shape) {
          double X = pow(U, 1.0 / shape);
          if (X <= V) return X;
        } else {
          double Y = -log((1.0 - U) / shape);
          double X = pow(1.0 - shape + shape * Y, 1.0 / shape);
          if (X <= V + Y) return X;
        }
      }
    }
    double b = shape - 1.0 / 3.0;
    double c = 1.0 / sqrt(9.0 * b);
    for (;;) {
      double X, V;
      do {
        X = standard_normal();
        V = 1.0 + c * X;
      } while (V <= 0.0);
      V = V * V * V;
      double U = random();
      if (U < 1.0 - 0.0331 * (X * X) * (X * X)) return b * V;
      // log(0.0) = -inf ACCEPTS (-inf < finite rhs), matching numpy's bare
      // log(U) compare
      if (log(U) < 0.5 * X * X + b * (1.0 - V + log(V))) return b * V;
    }
  }

  // random_beta: Johnk when both shapes <= 1, else two gammas
  double beta(double a, double b) {
    if (a <= 1.0 && b <= 1.0) {
      for (;;) {
        double U = random();
        double V = random();
        double X = pow(U, 1.0 / a);
        double Y = pow(V, 1.0 / b);
        double XpY = X + Y;
        // numpy rejects only when BOTH uniforms are 0; when the pows
        // underflow (tiny shapes) it answers in log space instead
        if (XpY <= 1.0 && U + V > 0.0) {
          if (XpY > 0) return X / XpY;
          double logX = log(U) / a;
          double logY = log(V) / b;
          double logM = logX > logY ? logX : logY;
          logX -= logM;
          logY -= logM;
          return exp(logX - log(exp(logX) + exp(logY)));
        }
      }
    }
    double Ga = standard_gamma(a);
    double Gb = standard_gamma(b);
    return Ga / (Ga + Gb);
  }
};

// ---------------------------------------------------------------------------
// CPython random.Random(seed): MT19937 + init_by_array + _randbelow
// ---------------------------------------------------------------------------
struct PyRng {
  static constexpr int N = 624, M = 397;
  static constexpr uint32_t MATRIX_A = 0x9908b0dfu;
  static constexpr uint32_t UPPER_MASK = 0x80000000u, LOWER_MASK = 0x7fffffffu;

  uint32_t mt[N];
  int mti = N + 1;

  explicit PyRng(uint64_t seed) {
    // CPython random_seed: key = abs(seed) as 32-bit little-endian words
    std::vector<uint32_t> key;
    if (seed == 0) {
      key.push_back(0);
    } else {
      uint64_t s = seed;
      while (s) {
        key.push_back((uint32_t)s);
        s >>= 32;
      }
    }
    init_by_array(key.data(), (int)key.size());
  }

  void init_genrand(uint32_t s) {
    mt[0] = s;
    for (mti = 1; mti < N; ++mti)
      mt[mti] = 1812433253u * (mt[mti - 1] ^ (mt[mti - 1] >> 30)) + (uint32_t)mti;
  }

  void init_by_array(const uint32_t* key, int key_length) {
    init_genrand(19650218u);
    int i = 1, j = 0;
    int k = N > key_length ? N : key_length;
    for (; k; --k) {
      mt[i] = (mt[i] ^ ((mt[i - 1] ^ (mt[i - 1] >> 30)) * 1664525u)) + key[j] + (uint32_t)j;
      ++i;
      ++j;
      if (i >= N) {
        mt[0] = mt[N - 1];
        i = 1;
      }
      if (j >= key_length) j = 0;
    }
    for (k = N - 1; k; --k) {
      mt[i] = (mt[i] ^ ((mt[i - 1] ^ (mt[i - 1] >> 30)) * 1566083941u)) - (uint32_t)i;
      ++i;
      if (i >= N) {
        mt[0] = mt[N - 1];
        i = 1;
      }
    }
    mt[0] = 0x80000000u;
  }

  uint32_t genrand_uint32() {
    uint32_t y;
    if (mti >= N) {
      static const uint32_t mag01[2] = {0u, MATRIX_A};
      int kk;
      for (kk = 0; kk < N - M; ++kk) {
        y = (mt[kk] & UPPER_MASK) | (mt[kk + 1] & LOWER_MASK);
        mt[kk] = mt[kk + M] ^ (y >> 1) ^ mag01[y & 1];
      }
      for (; kk < N - 1; ++kk) {
        y = (mt[kk] & UPPER_MASK) | (mt[kk + 1] & LOWER_MASK);
        mt[kk] = mt[kk + (M - N)] ^ (y >> 1) ^ mag01[y & 1];
      }
      y = (mt[N - 1] & UPPER_MASK) | (mt[0] & LOWER_MASK);
      mt[N - 1] = mt[M - 1] ^ (y >> 1) ^ mag01[y & 1];
      mti = 0;
    }
    y = mt[mti++];
    y ^= y >> 11;
    y ^= (y << 7) & 0x9d2c5680u;
    y ^= (y << 15) & 0xefc60000u;
    y ^= y >> 18;
    return y;
  }

  // random_random: 53-bit double from two 32-bit draws
  double random() {
    uint32_t a = genrand_uint32() >> 5, b = genrand_uint32() >> 6;
    return (a * 67108864.0 + b) * (1.0 / 9007199254740992.0);
  }

  // getrandbits(k) for k <= 32
  uint32_t getrandbits(int k) { return genrand_uint32() >> (32 - k); }

  // Random._randbelow_with_getrandbits -> randrange(n)
  uint64_t randrange(uint64_t n) {
    if (n <= 1) return 0;
    int k = 64 - __builtin_clzll(n);  // CPython _randbelow: k = n.bit_length()
    uint32_t r = getrandbits(k);
    while (r >= n) r = getrandbits(k);
    return r;
  }
};

}  // namespace nprng
