// Closed-loop gRPC (HTTP/2) load generator for the native edge: N
// connections, K concurrent streams each, every stream a
// /seldon.protos.Seldon/Predict unary call with a 1x4 tensor payload (the
// gRPC twin of loadgen_http.cc; reference methodology:
// util/loadtester/scripts/predict_grpc_locust.py).

#include <algorithm>
#include <cerrno>
#include <cinttypes>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>

namespace {

uint64_t now_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + ts.tv_nsec;
}

void frame_header(std::string& out, uint32_t len, uint8_t type, uint8_t flags,
                  uint32_t sid) {
  char h[9] = {(char)(len >> 16), (char)(len >> 8), (char)len, (char)type,
               (char)flags, (char)(sid >> 24), (char)(sid >> 16),
               (char)(sid >> 8), (char)sid};
  out.append(h, 9);
}

// Minimal proto writer for the request message.
void pb_varint(std::string& b, uint64_t v) {
  while (v >= 0x80) {
    b.push_back((char)(v | 0x80));
    v >>= 7;
  }
  b.push_back((char)v);
}
void pb_tag(std::string& b, uint32_t f, uint32_t w) { pb_varint(b, f << 3 | w); }

std::string build_request_msg() {
  // SeldonMessage{data{tensor{shape:[1,4] values:[1,2,3,4]}}}
  std::string shape;
  pb_varint(shape, 1);
  pb_varint(shape, 4);
  std::string values;
  for (double v : {1.0, 2.0, 3.0, 4.0}) values.append((const char*)&v, 8);
  std::string tensor;
  pb_tag(tensor, 1, 2);
  pb_varint(tensor, shape.size());
  tensor += shape;
  pb_tag(tensor, 2, 2);
  pb_varint(tensor, values.size());
  tensor += values;
  std::string data;
  pb_tag(data, 2, 2);
  pb_varint(data, tensor.size());
  data += tensor;
  std::string msg;
  pb_tag(msg, 3, 2);
  pb_varint(msg, data.size());
  msg += data;
  return msg;
}

std::string build_headers_block(const char* authority) {
  std::string b;
  b.push_back((char)0x83);  // :method POST
  b.push_back((char)0x86);  // :scheme http
  b.push_back((char)0x04);  // :path, literal w/o indexing, name idx 4
  const char* path = "/seldon.protos.Seldon/Predict";
  b.push_back((char)strlen(path));
  b += path;
  b.push_back((char)0x01);  // :authority, name idx 1
  b.push_back((char)strlen(authority));
  b += authority;
  b.push_back((char)0x0f);  // content-type, name idx 31 (15 + 16)
  b.push_back((char)0x10);
  b.push_back((char)16);
  b += "application/grpc";
  b.push_back((char)0x00);  // te: trailers, new name
  b.push_back((char)2);
  b += "te";
  b.push_back((char)8);
  b += "trailers";
  return b;
}

struct Conn {
  int fd = -1;
  std::string inbuf;
  std::string outbuf;
  uint32_t next_sid = 1;
  uint32_t recv_unacked = 0;
  std::unordered_map<uint32_t, uint64_t> t_send;
};

struct Stats {
  uint64_t shed = 0;
  std::vector<uint32_t> lat_us;
  uint64_t ok = 0, errors = 0;
};

int connect_to(const char* host, int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  if (getaddrinfo(host, nullptr, &hints, &res) != 0 || !res) {
    fprintf(stderr, "cannot resolve %s\n", host);
    close(fd);
    return -1;
  }
  addr.sin_addr = ((sockaddr_in*)res->ai_addr)->sin_addr;
  freeaddrinfo(res);
  if (connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    close(fd);
    return -1;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  int flags = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, flags | O_NONBLOCK);
  return fd;
}

}  // namespace

int main(int argc, char** argv) {
  const char* host = "127.0.0.1";
  int port = 8001;
  int connections = 16;
  int streams_per_conn = 8;
  double duration_s = 10.0, warmup_s = 1.0;
  const char* label = "grpc";
  for (int i = 1; i < argc; ++i) {
    std::string_view a = argv[i];
    auto next = [&]() -> const char* { return i + 1 < argc ? argv[++i] : ""; };
    if (a == "--host") host = next();
    else if (a == "--port") port = atoi(next());
    else if (a == "--connections") connections = atoi(next());
    else if (a == "--streams") streams_per_conn = atoi(next());
    else if (a == "--duration") duration_s = atof(next());
    else if (a == "--warmup") warmup_s = atof(next());
    else if (a == "--label") label = next();
    else { fprintf(stderr, "unknown arg %s\n", argv[i]); return 2; }
  }
  signal(SIGPIPE, SIG_IGN);

  char authority[128];
  snprintf(authority, sizeof(authority), "%s:%d", host, port);
  std::string headers_block = build_headers_block(authority);
  std::string msg = build_request_msg();
  std::string grpc_frame;
  grpc_frame.push_back(0);
  uint32_t ml = (uint32_t)msg.size();
  grpc_frame.push_back((char)(ml >> 24));
  grpc_frame.push_back((char)(ml >> 16));
  grpc_frame.push_back((char)(ml >> 8));
  grpc_frame.push_back((char)ml);
  grpc_frame += msg;

  std::vector<Conn> conns(connections);
  int epfd = epoll_create1(0);
  for (int i = 0; i < connections; ++i) {
    Conn& c = conns[i];
    c.fd = connect_to(host, port);
    if (c.fd < 0) {
      fprintf(stderr, "connect failed\n");
      return 1;
    }
    c.outbuf += "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
    frame_header(c.outbuf, 0, 4, 0, 0);  // empty SETTINGS
    // open the connection receive window wide
    frame_header(c.outbuf, 4, 8, 0, 0);
    uint32_t inc = 0x7fffffff - 65535;
    char wu[4] = {(char)(inc >> 24), (char)(inc >> 16), (char)(inc >> 8), (char)inc};
    c.outbuf.append(wu, 4);
    epoll_event ev{};
    ev.events = EPOLLIN | EPOLLOUT;
    ev.data.u32 = (uint32_t)i;
    epoll_ctl(epfd, EPOLL_CTL_ADD, c.fd, &ev);
  }

  auto start_stream = [&](Conn& c) {
    uint32_t sid = c.next_sid;
    c.next_sid += 2;
    frame_header(c.outbuf, (uint32_t)headers_block.size(), 1, 0x4, sid);
    c.outbuf += headers_block;
    frame_header(c.outbuf, (uint32_t)grpc_frame.size(), 0, 0x1, sid);
    c.outbuf += grpc_frame;
    c.t_send[sid] = now_ns();
  };
  for (auto& c : conns)
    for (int s = 0; s < streams_per_conn; ++s) start_stream(c);

  Stats stats;
  stats.lat_us.reserve(1 << 20);
  uint64_t t_measure = now_ns() + (uint64_t)(warmup_s * 1e9);
  uint64_t t_end = t_measure + (uint64_t)(duration_s * 1e9);
  bool measuring = warmup_s <= 0;

  auto flush = [&](Conn& c) {
    while (!c.outbuf.empty()) {
      ssize_t n = ::send(c.fd, c.outbuf.data(), c.outbuf.size(), MSG_NOSIGNAL);
      if (n > 0) {
        c.outbuf.erase(0, (size_t)n);
        continue;
      }
      if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) return;
      fprintf(stderr, "send failed\n");
      exit(1);
    }
  };

  std::vector<epoll_event> events(256);
  char rbuf[65536];
  for (;;) {
    uint64_t now = now_ns();
    if (now >= t_end) break;
    if (!measuring && now >= t_measure) {
      measuring = true;
      stats.ok = stats.errors = 0;
      stats.lat_us.clear();
    }
    int n = epoll_wait(epfd, events.data(), (int)events.size(), 100);
    for (int i = 0; i < n; ++i) {
      Conn& c = conns[events[i].data.u32];
      flush(c);
      for (;;) {
        ssize_t got = ::recv(c.fd, rbuf, sizeof(rbuf), 0);
        if (got > 0) {
          c.inbuf.append(rbuf, (size_t)got);
          if (got < (ssize_t)sizeof(rbuf)) break;
          continue;
        }
        if (got == 0) {
          fprintf(stderr, "server closed connection\n");
          return 1;
        }
        if (errno == EAGAIN || errno == EWOULDBLOCK) break;
        fprintf(stderr, "recv error\n");
        return 1;
      }
      // parse frames
      size_t off = 0;
      while (c.inbuf.size() - off >= 9) {
        const uint8_t* h = (const uint8_t*)c.inbuf.data() + off;
        uint32_t len = (h[0] << 16) | (h[1] << 8) | h[2];
        uint8_t type = h[3], flags = h[4];
        uint32_t sid = ((h[5] & 0x7f) << 24) | (h[6] << 16) | (h[7] << 8) | h[8];
        if (c.inbuf.size() - off < 9 + len) break;
        std::string_view payload{c.inbuf.data() + off + 9, len};
        off += 9 + len;
        switch (type) {
          case 0:  // DATA
            c.recv_unacked += len;
            break;
          case 1:  // HEADERS (response or trailers)
            if (flags & 0x1) {  // END_STREAM -> trailers: stream complete
              auto it = c.t_send.find(sid);
              if (it != c.t_send.end()) {
                uint64_t lat = now_ns() - it->second;
                bool ok = payload.find("grpc-status") == std::string_view::npos ||
                          payload.find(std::string_view("grpc-status\x01"
                                                        "0", 13)) !=
                              std::string_view::npos;
                // RESOURCE_EXHAUSTED (status 8) = deterministic overload
                // shed: well-formed by design, counted apart from failures
                bool is_shed = !ok && payload.find(std::string_view(
                                          "grpc-status\x01"
                                          "8", 13)) != std::string_view::npos;
                if (measuring) {
                  if (ok) ++stats.ok;
                  else if (is_shed) ++stats.shed;
                  else ++stats.errors;
                  // percentiles describe SERVED requests only (see
                  // loadgen_http.cc: sheds are near-instant by design)
                  if (ok) stats.lat_us.push_back((uint32_t)(lat / 1000));
                }
                c.t_send.erase(it);
                start_stream(c);
              }
            }
            break;
          case 3:  // RST_STREAM
            if (c.t_send.erase(sid)) {
              if (measuring) ++stats.errors;
              start_stream(c);
            }
            break;
          case 4:  // SETTINGS
            if (!(flags & 0x1)) frame_header(c.outbuf, 0, 4, 0x1, 0);
            break;
          case 6:  // PING
            if (!(flags & 0x1)) {
              frame_header(c.outbuf, len, 6, 0x1, 0);
              c.outbuf.append(payload);
            }
            break;
          default:
            break;
        }
      }
      if (off > 0) c.inbuf.erase(0, off);
      if (c.recv_unacked >= (1u << 15)) {
        frame_header(c.outbuf, 4, 8, 0, 0);
        char wu[4] = {(char)(c.recv_unacked >> 24), (char)(c.recv_unacked >> 16),
                      (char)(c.recv_unacked >> 8), (char)c.recv_unacked};
        c.outbuf.append(wu, 4);
        c.recv_unacked = 0;
      }
      flush(c);
    }
  }
  double elapsed = 1e-9 * (now_ns() - t_measure);
  std::sort(stats.lat_us.begin(), stats.lat_us.end());
  auto pct = [&](double p) -> double {
    if (stats.lat_us.empty()) return 0;
    size_t idx = (size_t)(p / 100.0 * stats.lat_us.size());
    if (idx >= stats.lat_us.size()) idx = stats.lat_us.size() - 1;
    return stats.lat_us[idx] / 1000.0;
  };
  double mean = 0;
  for (auto v : stats.lat_us) mean += v;
  mean = stats.lat_us.empty() ? 0 : mean / stats.lat_us.size() / 1000.0;
  printf("{\"label\": \"%s\", \"throughput_rps\": %.2f, \"requests\": %" PRIu64
         ", \"failures\": %" PRIu64 ", \"shed\": %" PRIu64
         ", \"duration_s\": %.2f, \"connections\": %d, \"streams_per_conn\": %d, "
         "\"latency_ms\": {\"mean\": %.3f, \"p50\": %.3f, \"p75\": %.3f, "
         "\"p90\": %.3f, \"p95\": %.3f, \"p98\": %.3f, \"p99\": %.3f, "
         "\"max\": %.3f}}\n",
         label, stats.ok / elapsed, stats.ok, stats.errors, stats.shed,
         elapsed, connections, streams_per_conn, mean, pct(50), pct(75),
         pct(90), pct(95), pct(98), pct(99),
         stats.lat_us.empty() ? 0 : stats.lat_us.back() / 1000.0);
  return stats.errors == 0 ? 0 : 3;
}
