// seldon-edge: native HTTP serving edge for the TPU engine.
//
// Role. The reference's published benchmark measures its compiled (Java)
// orchestrator running in-engine stub units — "the orchestrator +
// serialization ceiling, not model compute" (BASELINE.md; reference
// doc/source/reference/benchmarking.md:19-36, SimpleModelUnit.java:33-64).
// The TPU build's orchestrator ceiling lives here: a compiled edge that
// owns the HTTP external API (RestClientController.java:76-245 parity),
// executes graphs of builtin units natively when the whole graph compiles
// to an "edge program" (SIMPLE_MODEL / SIMPLE_ROUTER / RANDOM_ABTEST /
// AVERAGE_COMBINER — PredictorConfigBean.java:77-82), and otherwise
// forwards requests over the shared-memory ring (ring.cc) to the
// device-owning Python/XLA engine process. Python stays the brain (graph
// build, XLA compute, control plane); C++ owns the per-request byte work:
// HTTP parse, JSON decode/encode, puid generation, metrics.
//
// Design notes.
// - Single-threaded epoll event loop per worker; --workers N forks N loops
//   sharing the port via SO_REUSEPORT (one is optimal on a 1-core host;
//   real hosts scale linearly).
// - Zero allocations on the hot path after warm-up: per-connection growable
//   buffers are reused; responses are assembled into a scratch buffer.
// - Response floats print like Python repr (shortest round-trip) so native
//   and Python engines produce byte-comparable payloads.
// - The ring fallback polls with a timerfd while requests are in flight;
//   the Python engine side is seldon_core_tpu/transport/ipc.py.

#include <algorithm>
#include <atomic>
#include <cctype>
#include <deque>
#include <cerrno>
#include <cinttypes>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <functional>
#include <memory>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <sys/timerfd.h>
#include <sys/wait.h>
#include <unistd.h>

#include "np_rng.h"

// ---------------------------------------------------------------------------
// Shared ring (ring.cc) — linked in; used for the Python-engine fallback.
// ---------------------------------------------------------------------------
extern "C" {
void* scr_create(const char* path, uint64_t capacity, uint64_t slot_size);
void* scr_attach(const char* path);
void scr_detach(void* h);
uint64_t scr_slot_size(void* h);
int scr_push(void* h, const void* data, uint32_t len);
int scr_pop(void* h, void* out, uint32_t cap);
}

namespace {

// ---------------------------------------------------------------------------
// Small utils
// ---------------------------------------------------------------------------

struct Buf {
  std::vector<char> v;
  void clear() { v.clear(); }
  size_t size() const { return v.size(); }
  const char* data() const { return v.data(); }
  void append(const char* p, size_t n) { v.insert(v.end(), p, p + n); }
  void append(std::string_view s) { append(s.data(), s.size()); }
  void push(char c) { v.push_back(c); }
  void append_u64(uint64_t x) {
    char tmp[24];
    int n = snprintf(tmp, sizeof(tmp), "%" PRIu64, x);
    append(tmp, n);
  }
  void append_i64(int64_t x) {
    char tmp[24];
    int n = snprintf(tmp, sizeof(tmp), "%" PRId64, x);
    append(tmp, n);
  }
  // Shortest round-trip double formatting (Python repr parity).
  void append_double(double x) {
    char tmp[32];
    for (int prec = 1; prec <= 17; ++prec) {
      int n = snprintf(tmp, sizeof(tmp), "%.*g", prec, x);
      double back = strtod(tmp, nullptr);
      if (back == x) {
        // Python renders integral floats as "1.0", %g as "1" — fix up.
        bool has_dot = false;
        for (int i = 0; i < n; ++i)
          if (tmp[i] == '.' || tmp[i] == 'e' || tmp[i] == 'n' || tmp[i] == 'i') has_dot = true;
        append(tmp, n);
        if (!has_dot) append(".0");
        return;
      }
    }
    append(tmp, strlen(tmp));
  }
  void append_json_escaped(std::string_view s) {
    for (char c : s) {
      switch (c) {
        case '"': append("\\\""); break;
        case '\\': append("\\\\"); break;
        case '\n': append("\\n"); break;
        case '\r': append("\\r"); break;
        case '\t': append("\\t"); break;
        default:
          if (static_cast<unsigned char>(c) < 0x20) {
            char tmp[8];
            append(tmp, snprintf(tmp, sizeof(tmp), "\\u%04x", c));
          } else {
            push(c);
          }
      }
    }
  }
};

// xorshift128+ puid generator (entropy class of the reference's SecureRandom
// 130-bit id, service/PredictionService.java:77-83; speed matters here).
struct Rng {
  uint64_t s0 = 0, s1 = 0;
  void seed() {
    FILE* f = fopen("/dev/urandom", "rb");
    if (f) {
      size_t got = fread(&s0, 8, 1, f) + fread(&s1, 8, 1, f);
      (void)got;
      fclose(f);
    }
    if (!s0) s0 = 0x9e3779b97f4a7c15ull ^ getpid();
    if (!s1) s1 = 0xbf58476d1ce4e5b9ull ^ (uint64_t)&s0;
  }
  uint64_t next() {
    uint64_t x = s0, y = s1;
    s0 = y;
    x ^= x << 23;
    s1 = x ^ y ^ (x >> 17) ^ (y >> 26);
    return s1 + y;
  }
  double uniform() { return (next() >> 11) * (1.0 / 9007199254740992.0); }
  // Marsaglia polar method (no trig); spare cached like numpy's legacy gauss.
  double normal() {
    if (have_spare) {
      have_spare = false;
      return spare;
    }
    double u, v, s;
    do {
      u = 2.0 * uniform() - 1.0;
      v = 2.0 * uniform() - 1.0;
      s = u * u + v * v;
    } while (s >= 1.0 || s == 0.0);
    double m = sqrt(-2.0 * log(s) / s);
    spare = v * m;
    have_spare = true;
    return u * m;
  }
  // Marsaglia-Tsang; Thompson posteriors have shape = prior + mass >= 1 but
  // the boost branch keeps it correct for shape < 1 anyway.
  double gamma(double shape) {
    if (shape < 1.0) {
      double u = uniform();
      while (u == 0.0) u = uniform();
      return gamma(shape + 1.0) * pow(u, 1.0 / shape);
    }
    double d = shape - 1.0 / 3.0;
    double c = 1.0 / sqrt(9.0 * d);
    for (;;) {
      double x = normal();
      double v = 1.0 + c * x;
      if (v <= 0) continue;
      v = v * v * v;
      double u = uniform();
      if (u < 1.0 - 0.0331 * x * x * x * x) return d * v;
      if (u > 0.0 && log(u) < 0.5 * x * x + d * (1.0 - v + log(v))) return d * v;
    }
  }
  double beta(double a, double b) {
    double x = gamma(a);
    double y = gamma(b);
    return x / (x + y);
  }
  bool have_spare = false;
  double spare = 0;
  void puid_hex(char out[33]) {
    static const char* hex = "0123456789abcdef";
    uint64_t a = next(), b = next();
    for (int i = 0; i < 16; ++i) out[i] = hex[(a >> (i * 4)) & 15];
    for (int i = 0; i < 16; ++i) out[16 + i] = hex[(b >> (i * 4)) & 15];
    out[32] = 0;
  }
};

// ---------------------------------------------------------------------------
// Minimal JSON parser (DOM over string_views into the request buffer).
// ---------------------------------------------------------------------------

struct JValue;
using JMember = std::pair<std::string_view, int>;  // key -> node index

struct JValue {
  enum Type { Null, Bool, Num, Str, Arr, Obj } type = Null;
  std::string_view raw;     // full span (for verbatim echo)
  std::string_view sv;      // string contents (unescaped lazily) / number text
  bool b = false;
  int first_child = -1;     // Arr/Obj: index into nodes/members
  int n_children = 0;
};

struct JDoc {
  std::vector<JValue> nodes;
  std::vector<int> arr_items;       // flattened child lists
  std::vector<JMember> obj_members; // flattened member lists
  const char* err = nullptr;

  const JValue* get(const JValue& obj, std::string_view key) const {
    if (obj.type != JValue::Obj) return nullptr;
    for (int i = 0; i < obj.n_children; ++i) {
      const auto& m = obj_members[obj.first_child + i];
      if (m.first == key) return &nodes[m.second];
    }
    return nullptr;
  }
  const JValue* item(const JValue& arr, int i) const {
    if (arr.type != JValue::Arr || i >= arr.n_children) return nullptr;
    return &nodes[arr_items[arr.first_child + i]];
  }
};

struct JParser {
  const char* p;
  const char* end;
  JDoc* doc;

  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
  }
  bool fail(const char* msg) {
    if (!doc->err) doc->err = msg;
    return false;
  }
  // returns node index or -1
  int parse_value() {
    skip_ws();
    if (p >= end) return fail("unexpected end"), -1;
    const char* start = p;
    int idx = (int)doc->nodes.size();
    doc->nodes.emplace_back();
    switch (*p) {
      case '{': {
        ++p;
        std::vector<JMember> members;
        skip_ws();
        if (p < end && *p == '}') {
          ++p;
        } else {
          for (;;) {
            skip_ws();
            if (p >= end || *p != '"') return fail("expected key"), -1;
            std::string_view key;
            if (!parse_string_into(key)) return -1;
            skip_ws();
            if (p >= end || *p != ':') return fail("expected ':'"), -1;
            ++p;
            int child = parse_value();
            if (child < 0) return -1;
            members.push_back({key, child});
            skip_ws();
            if (p < end && *p == ',') {
              ++p;
              continue;
            }
            if (p < end && *p == '}') {
              ++p;
              break;
            }
            return fail("expected ',' or '}'"), -1;
          }
        }
        JValue& v = doc->nodes[idx];
        v.type = JValue::Obj;
        v.first_child = (int)doc->obj_members.size();
        v.n_children = (int)members.size();
        for (auto& m : members) doc->obj_members.push_back(m);
        v.raw = {start, (size_t)(p - start)};
        return idx;
      }
      case '[': {
        ++p;
        std::vector<int> items;
        skip_ws();
        if (p < end && *p == ']') {
          ++p;
        } else {
          for (;;) {
            int child = parse_value();
            if (child < 0) return -1;
            items.push_back(child);
            skip_ws();
            if (p < end && *p == ',') {
              ++p;
              continue;
            }
            if (p < end && *p == ']') {
              ++p;
              break;
            }
            return fail("expected ',' or ']'"), -1;
          }
        }
        JValue& v = doc->nodes[idx];
        v.type = JValue::Arr;
        v.first_child = (int)doc->arr_items.size();
        v.n_children = (int)items.size();
        for (int it : items) doc->arr_items.push_back(it);
        v.raw = {start, (size_t)(p - start)};
        return idx;
      }
      case '"': {
        std::string_view s;
        if (!parse_string_into(s)) return -1;
        JValue& v = doc->nodes[idx];
        v.type = JValue::Str;
        v.sv = s;
        v.raw = {start, (size_t)(p - start)};
        return idx;
      }
      case 't':
        if (end - p >= 4 && !memcmp(p, "true", 4)) {
          p += 4;
          JValue& v = doc->nodes[idx];
          v.type = JValue::Bool;
          v.b = true;
          v.raw = {start, 4};
          return idx;
        }
        return fail("bad literal"), -1;
      case 'f':
        if (end - p >= 5 && !memcmp(p, "false", 5)) {
          p += 5;
          JValue& v = doc->nodes[idx];
          v.type = JValue::Bool;
          v.raw = {start, 5};
          return idx;
        }
        return fail("bad literal"), -1;
      case 'n':
        if (end - p >= 4 && !memcmp(p, "null", 4)) {
          p += 4;
          JValue& v = doc->nodes[idx];
          v.type = JValue::Null;
          v.raw = {start, 4};
          return idx;
        }
        return fail("bad literal"), -1;
      default: {
        const char* q = p;
        if (q < end && (*q == '-' || *q == '+')) ++q;
        while (q < end && (isdigit((unsigned char)*q) || *q == '.' || *q == 'e' ||
                           *q == 'E' || *q == '-' || *q == '+'))
          ++q;
        if (q == p) return fail("bad value"), -1;
        JValue& v = doc->nodes[idx];
        v.type = JValue::Num;
        v.sv = {p, (size_t)(q - p)};
        v.raw = v.sv;
        p = q;
        return idx;
      }
    }
  }
  bool parse_string_into(std::string_view& out) {
    // *p == '"'
    ++p;
    const char* s = p;
    while (p < end && *p != '"') {
      if (*p == '\\') ++p;  // skip escaped char (slice keeps escapes; fine for
                            // keys/compares which are ASCII in our schema)
      ++p;
    }
    if (p >= end) return fail("unterminated string");
    out = {s, (size_t)(p - s)};
    ++p;
    return true;
  }
};

bool json_parse(const char* data, size_t len, JDoc& doc) {
  doc.nodes.clear();
  doc.arr_items.clear();
  doc.obj_members.clear();
  doc.err = nullptr;
  doc.nodes.reserve(64);
  JParser parser{data, data + len, &doc};
  int root = parser.parse_value();
  if (root < 0) return false;
  parser.skip_ws();
  if (parser.p != parser.end) {
    doc.err = "trailing data";
    return false;
  }
  return true;
}

double jnum(const JValue& v) { return strtod(std::string(v.sv).c_str(), nullptr); }

// Python-engine parity for meta.routing values: Meta.from_dict applies
// int(v), which truncates floats, parses integer strings (surrounding
// whitespace, optional sign, underscores between digits), and maps
// true/false to 1/0 — and raises on anything else (so the engine 400s
// MICROSERVICE_BAD_DATA). Values int() accepts but that name no real
// branch fail later in feedback_walk as BAD_ROUTING, exactly like the
// engine; out-of-int-range magnitudes are clamped (never a valid branch,
// so the response is the same BAD_ROUTING either way). Known divergence:
// python int() also accepts non-ASCII unicode digits; those 400 here.
bool routing_value_to_int(const JValue& v, int& out) {
  if (v.type == JValue::Num) {
    double d = jnum(v);  // int(1.9) == 1 (truncation)
    if (d != d || d == __builtin_inf() || d == -__builtin_inf())
      return false;  // int(inf/nan) raises in python
    if (d >= 2147483647.0) { out = 2147483647; return true; }
    if (d <= -2147483648.0) { out = -2147483647 - 1; return true; }
    out = (int)d;
    return true;
  }
  if (v.type == JValue::Bool) {
    out = v.b ? 1 : 0;
    return true;
  }
  if (v.type == JValue::Str) {
    const char* p = v.sv.data();
    const char* end = p + v.sv.size();
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
    while (end > p && (end[-1] == ' ' || end[-1] == '\t' || end[-1] == '\n' ||
                       end[-1] == '\r')) --end;
    bool neg = false;
    if (p < end && (*p == '+' || *p == '-')) neg = (*p++ == '-');
    if (p == end) return false;
    long long val = 0;
    bool prev_digit = false;
    for (; p < end; ++p) {
      if (*p == '_') {  // int("1_0") == 10; "_1"/"1__0"/"1_" raise
        if (!prev_digit || p + 1 == end || p[1] == '_') return false;
        prev_digit = false;
        continue;
      }
      if (*p < '0' || *p > '9') return false;  // int("1.5") raises in python
      prev_digit = true;
      if (val <= 2147483647LL) val = val * 10 + (*p - '0');
    }
    if (val > 2147483647LL) val = 2147483647LL;  // clamp -> BAD_ROUTING later
    out = (int)(neg ? -val : val);
    return true;
  }
  return false;  // null / arrays / objects: int(v) raises
}

// ---------------------------------------------------------------------------
// Edge program: the natively-executable graph.
// ---------------------------------------------------------------------------

enum class Kind { DeviceModel, DeviceTransform, SimpleModel, SimpleRouter, RandomABTest, AverageCombiner,
                  EpsilonGreedy, ThompsonSampling };

inline bool is_bandit(Kind k) {
  return k == Kind::EpsilonGreedy || k == Kind::ThompsonSampling;
}

struct Unit {
  std::string name;
  Kind kind;
  std::vector<int> children;
  // DEVICE_MODEL: real model executed by the engine process's ModelExecutor
  // over the ring (transport/ipc.py kind 2); the edge ships only the tensor.
  int model_id = -1;
  std::string class_name;  // requestPath value, e.g. "JAXServer"
  double ratioA = 0.5;
  int n_branches = 2;
  // bandit parameters + per-process learned state (analytics/routers.py
  // _BanditRouter: pulls / reward_sum / fail_sum per branch, rewards clamped
  // to [0,1]). Each edge worker learns from the feedback it receives — the
  // same per-replica-state model as multi-replica Python engines before a
  // G-counter sync round.
  double epsilon = 0.1;
  int best_branch = 0;
  double alpha0 = 1.0, beta0 = 1.0;
  // Seeded units replay the Python stream exactly (np_rng.h): numpy PCG64
  // for the bandits, CPython MT19937 for RandomABTest — so seeded graphs
  // serve natively with request-for-request routing parity.
  std::shared_ptr<nprng::NpRng> np_rng;
  std::shared_ptr<nprng::PyRng> py_rng;
  mutable std::vector<uint64_t> pulls;
  mutable std::vector<double> reward_sum, fail_sum;

  void init_bandit_state() {
    pulls.assign(n_branches, 0);
    reward_sum.assign(n_branches, 0.0);
    fail_sum.assign(n_branches, 0.0);
  }
};

struct Program {
  std::string deployment, predictor;
  std::vector<Unit> units;
  int root = -1;
  bool native = false;  // false => every request goes over the ring
  bool has_device = false;  // any DEVICE_MODEL unit (needs the ring too)
};

const char* kind_class(Kind k) {
  switch (k) {
    case Kind::DeviceModel: return "DeviceModel";  // overridden by class_name
    case Kind::DeviceTransform: return "DeviceTransform";  // ditto
    case Kind::SimpleModel: return "SimpleModel";
    case Kind::SimpleRouter: return "SimpleRouter";
    case Kind::RandomABTest: return "RandomABTest";
    case Kind::AverageCombiner: return "AverageCombiner";
    case Kind::EpsilonGreedy: return "EpsilonGreedy";
    case Kind::ThompsonSampling: return "ThompsonSampling";
  }
  return "";
}

bool load_program(const char* path, Program& prog) {
  FILE* f = fopen(path, "rb");
  if (!f) return false;
  std::string text;
  char tmp[4096];
  size_t n;
  while ((n = fread(tmp, 1, sizeof(tmp), f)) > 0) text.append(tmp, n);
  fclose(f);
  JDoc doc;
  if (!json_parse(text.data(), text.size(), doc)) return false;
  const JValue& rootv = doc.nodes[0];
  if (auto* d = doc.get(rootv, "deployment")) prog.deployment = std::string(d->sv);
  if (auto* d = doc.get(rootv, "predictor")) prog.predictor = std::string(d->sv);
  auto* nat = doc.get(rootv, "native");
  prog.native = nat && nat->b;
  if (!prog.native) return true;
  auto* units = doc.get(rootv, "units");
  auto* rootidx = doc.get(rootv, "root");
  if (!units || !rootidx) return false;
  for (int i = 0; i < units->n_children; ++i) {
    const JValue& u = *doc.item(*units, i);
    Unit unit;
    if (auto* v = doc.get(u, "name")) unit.name = std::string(v->sv);
    std::string kind;
    if (auto* v = doc.get(u, "kind")) kind = std::string(v->sv);
    if (kind == "SIMPLE_MODEL") unit.kind = Kind::SimpleModel;
    else if (kind == "SIMPLE_ROUTER") unit.kind = Kind::SimpleRouter;
    else if (kind == "RANDOM_ABTEST") unit.kind = Kind::RandomABTest;
    else if (kind == "AVERAGE_COMBINER") unit.kind = Kind::AverageCombiner;
    else if (kind == "EPSILON_GREEDY") unit.kind = Kind::EpsilonGreedy;
    else if (kind == "THOMPSON_SAMPLING") unit.kind = Kind::ThompsonSampling;
    else if (kind == "DEVICE_MODEL") {
      unit.kind = Kind::DeviceModel;
      prog.has_device = true;
    }
    else if (kind == "DEVICE_TRANSFORM") {
      unit.kind = Kind::DeviceTransform;
      prog.has_device = true;
    }
    else return false;
    if (auto* v = doc.get(u, "modelId")) unit.model_id = (int)jnum(*v);
    if (auto* v = doc.get(u, "className")) unit.class_name = std::string(v->sv);
    if ((unit.kind == Kind::DeviceModel || unit.kind == Kind::DeviceTransform) &&
        unit.model_id < 0)
      return false;
    if (unit.kind == Kind::DeviceTransform && unit.children.size() != 1 &&
        !unit.children.empty())
      return false;
    if (auto* v = doc.get(u, "ratioA")) unit.ratioA = jnum(*v);
    if (auto* v = doc.get(u, "nBranches")) unit.n_branches = (int)jnum(*v);
    if (auto* v = doc.get(u, "epsilon")) unit.epsilon = jnum(*v);
    if (auto* v = doc.get(u, "bestBranch")) unit.best_branch = (int)jnum(*v);
    if (auto* v = doc.get(u, "alpha")) unit.alpha0 = jnum(*v);
    if (auto* v = doc.get(u, "beta")) unit.beta0 = jnum(*v);
    if (auto* v = doc.get(u, "seed")) {
      uint64_t seed = (uint64_t)jnum(*v);
      if (unit.kind == Kind::RandomABTest)
        unit.py_rng = std::make_shared<nprng::PyRng>(seed);
      else
        unit.np_rng = std::make_shared<nprng::NpRng>(seed);
    }
    if (auto* v = doc.get(u, "children"))
      for (int c = 0; c < v->n_children; ++c)
        unit.children.push_back((int)jnum(*doc.item(*v, c)));
    if (is_bandit(unit.kind)) {
      if (unit.n_branches < 1) return false;
      unit.init_bandit_state();
    }
    prog.units.push_back(std::move(unit));
  }
  prog.root = (int)jnum(*rootidx);
  return prog.root >= 0 && prog.root < (int)prog.units.size();
}

// ---------------------------------------------------------------------------
// Metrics (Prometheus text exposition; name parity with metrics/registry.py)
// ---------------------------------------------------------------------------

constexpr double kBuckets[] = {0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                               0.05,   0.1,   0.25,   0.5,   1.0,  2.5, 5.0};
constexpr int kNBuckets = sizeof(kBuckets) / sizeof(kBuckets[0]);

struct Histo {
  uint64_t bucket[kNBuckets + 1] = {};
  double sum = 0;
  uint64_t count = 0;
  void observe(double v) {
    for (int i = 0; i < kNBuckets; ++i)
      if (v <= kBuckets[i]) ++bucket[i];
    ++bucket[kNBuckets];
    sum += v;
    ++count;
  }
};

struct Metrics {
  std::string deployment, predictor;
  // method/code counters for the engine API
  std::unordered_map<std::string, uint64_t> api;  // "method|code"
  std::unordered_map<std::string, Histo> latency; // method
  uint64_t feedback_events = 0;
  double feedback_reward = 0;
  // in-band custom metrics from builtin units
  double mycounter = 0;
  double mygauge = 0;
  Histo mytimer;
  uint64_t custom_seen = 0;

  uint64_t shed_total = 0;  // overload-shed predictions (429/RESOURCE_EXHAUSTED)

  void observe_api(const char* method, int code, double secs) {
    char key[64];
    snprintf(key, sizeof(key), "%s|%d", method, code);
    ++api[key];
    latency[method].observe(secs);
  }
  void labels(Buf& b, const char* extra = nullptr) {
    b.append("{deployment_name=\"");
    b.append_json_escaped(deployment);
    b.append("\",predictor_name=\"");
    b.append_json_escaped(predictor);
    b.push('"');
    if (extra) {
      b.push(',');
      b.append(extra);
    }
    b.push('}');
  }
  void expose(Buf& b) {
    b.append("# HELP seldon_edge_shed_total predictions shed under overload (HTTP 429 / gRPC RESOURCE_EXHAUSTED)\n");
    b.append("# TYPE seldon_edge_shed_total counter\n");
    b.append("seldon_edge_shed_total");
    labels(b);
    b.push(' ');
    b.append_double((double)shed_total);
    b.push('\n');
    b.append("# HELP seldon_api_executor_server_requests_total API requests by method and code\n");
    b.append("# TYPE seldon_api_executor_server_requests_total counter\n");
    for (auto& [key, count] : api) {
      auto bar = key.find('|');
      char extra[96];
      snprintf(extra, sizeof(extra), "method=\"%s\",code=\"%s\"",
               key.substr(0, bar).c_str(), key.substr(bar + 1).c_str());
      b.append("seldon_api_executor_server_requests_total");
      labels(b, extra);
      b.push(' ');
      b.append_double((double)count);
      b.push('\n');
    }
    b.append("# HELP seldon_api_executor_server_requests_seconds API latency\n");
    b.append("# TYPE seldon_api_executor_server_requests_seconds histogram\n");
    for (auto& [method, h] : latency) {
      uint64_t cum = 0;
      for (int i = 0; i <= kNBuckets; ++i) {
        cum = h.bucket[i];
        char extra[96];
        if (i < kNBuckets)
          snprintf(extra, sizeof(extra), "method=\"%s\",le=\"%g\"", method.c_str(), kBuckets[i]);
        else
          snprintf(extra, sizeof(extra), "method=\"%s\",le=\"+Inf\"", method.c_str());
        b.append("seldon_api_executor_server_requests_seconds_bucket");
        labels(b, extra);
        b.push(' ');
        b.append_u64(cum);
        b.push('\n');
      }
      char extra[96];
      snprintf(extra, sizeof(extra), "method=\"%s\"", method.c_str());
      b.append("seldon_api_executor_server_requests_seconds_sum");
      labels(b, extra);
      b.push(' ');
      b.append_double(h.sum);
      b.push('\n');
      b.append("seldon_api_executor_server_requests_seconds_count");
      labels(b, extra);
      b.push(' ');
      b.append_u64(h.count);
      b.push('\n');
    }
    b.append("# TYPE seldon_api_model_feedback_total counter\n");
    b.append("seldon_api_model_feedback_total");
    labels(b);
    b.push(' ');
    b.append_double((double)feedback_events);
    b.push('\n');
    b.append("# TYPE seldon_api_model_feedback_reward_total counter\n");
    b.append("seldon_api_model_feedback_reward_total");
    labels(b);
    b.push(' ');
    b.append_double(feedback_reward);
    b.push('\n');
    if (custom_seen) {
      b.append("# TYPE mycounter_total counter\nmycounter_total ");
      b.append_double(mycounter);
      b.append("\n# TYPE mygauge gauge\nmygauge ");
      b.append_double(mygauge);
      b.append("\n# TYPE mytimer histogram\nmytimer_sum ");
      b.append_double(mytimer.sum);
      b.append("\nmytimer_count ");
      b.append_u64(mytimer.count);
      b.push('\n');
    }
  }
};

// ---------------------------------------------------------------------------
// Native graph execution
// ---------------------------------------------------------------------------

enum class PKind { None, NDArray, Tensor, Str, Bin, Json };

struct Payload {
  PKind kind = PKind::None;
  int64_t rows = 0;
  std::string_view echo;  // raw span for strData/binData (with escapes)
};

struct ExecOut {
  // collected while walking
  std::vector<std::pair<std::string_view, int>> routing;  // router name -> branch
  std::vector<std::pair<std::string_view, const char*>> path;  // unit -> class
  // Bandit routers traversed, outermost first, with the branch-mean snapshot
  // taken at route time — the tags fragment the Python engine merges in
  // (routers.py tags(): {"bandit": cls, "branch_means": [...]}). The
  // outermost router's fragment wins (engine _merge_meta: target wins, and
  // the outer router's tags are already on the message when the inner one
  // merges).
  std::vector<std::pair<int, std::vector<double>>> bandit_tags;  // unit idx
  int model_visits = 0;
  Kind owner = Kind::SimpleModel;  // flow-final payload owner
  Payload out;
  const char* err = nullptr;
  int err_code = 0;
  const char* err_reason = nullptr;
  std::string err_info;
};

struct EdgeError {
  int code;
  const char* reason;
  std::string info;
};

// Recursive eval; returns flow-final payload owner kind. Never sees
// DeviceModel units — those graphs run eval_device (the handler branches
// on prog.has_device before reaching here).
bool eval_unit(const Program& prog, int idx, Rng& rng, Payload in, ExecOut& out,
               Payload& result, Kind& owner) {
  const Unit& u = prog.units[idx];
  switch (u.kind) {
    case Kind::DeviceModel:
    case Kind::DeviceTransform: {
      out.err_code = 500;
      out.err_reason = "INTERNAL_ERROR";
      out.err_info = "device unit reached the stub evaluator";
      return false;
    }
    case Kind::SimpleModel: {
      Payload mine;
      if (in.kind == PKind::Str || in.kind == PKind::Bin) {
        mine = in;  // echo (SimpleModelUnit echoes bytes/str)
      } else if (in.kind == PKind::NDArray || in.kind == PKind::Tensor) {
        mine.kind = in.kind;
        mine.rows = in.rows;
      } else if (in.kind == PKind::Json) {
        out.err_code = 500;
        out.err_reason = "INTERNAL_ERROR";
        out.err_info = "jsonData payload is not numeric";
        return false;
      } else {
        out.err_code = 400;
        out.err_reason = "MICROSERVICE_BAD_DATA";
        out.err_info =
            "Unknown data type returned as payload (must be array, list, str, "
            "bytes or dict): NoneType";
        return false;
      }
      ++out.model_visits;
      Payload final_out = mine;
      Kind sub_owner = Kind::SimpleModel;
      if (!u.children.empty()) {
        if (!eval_unit(prog, u.children[0], rng, mine, out, final_out, sub_owner))
          return false;
      }
      out.path.push_back({u.name, kind_class(u.kind)});
      result = final_out;
      owner = u.children.empty() ? Kind::SimpleModel : sub_owner;
      return true;
    }
    case Kind::SimpleRouter:
    case Kind::RandomABTest:
    case Kind::EpsilonGreedy:
    case Kind::ThompsonSampling: {
      int branch = 0;
      if (u.kind == Kind::RandomABTest) {
        if (u.py_rng) {  // seeded: CPython random.Random replay
          branch = u.n_branches == 2
                       ? (u.py_rng->random() < u.ratioA ? 0 : 1)
                       : (int)u.py_rng->randrange((uint64_t)u.n_branches);
        } else if (u.n_branches == 2)
          branch = rng.uniform() < u.ratioA ? 0 : 1;
        else
          branch = (int)(rng.uniform() * u.n_branches) % u.n_branches;
      } else if (u.kind == Kind::EpsilonGreedy) {
        // analytics/routers.py EpsilonGreedy.route: explore with prob eps,
        // else exploit argmax mean (best_branch before any feedback);
        // seeded units replay numpy default_rng draw-for-draw
        uint64_t total = 0;
        for (uint64_t p : u.pulls) total += p;
        double eps_draw = u.np_rng ? u.np_rng->random() : rng.uniform();
        if (eps_draw < u.epsilon) {
          branch = u.np_rng ? (int)u.np_rng->integers((uint64_t)u.n_branches)
                            : (int)(rng.next() % (uint64_t)u.n_branches);
        } else if (total == 0) {
          branch = u.best_branch;
        } else {
          double best = -1.0;
          for (int i = 0; i < u.n_branches; ++i) {
            double mean = u.reward_sum[i] / (double)(u.pulls[i] ? u.pulls[i] : 1);
            if (mean > best) {
              best = mean;
              branch = i;
            }
          }
        }
      } else if (u.kind == Kind::ThompsonSampling) {
        // theta_i ~ Beta(alpha0 + reward_i, beta0 + fail_i), argmax;
        // seeded units replay Generator.beta's elementwise array draw
        // (np_rng.h random_beta) so routing matches the Python engine
        // request-for-request
        double best = -1.0;
        for (int i = 0; i < u.n_branches; ++i) {
          double a = u.alpha0 + u.reward_sum[i], b = u.beta0 + u.fail_sum[i];
          double theta = u.np_rng ? u.np_rng->beta(a, b) : rng.beta(a, b);
          if (theta > best) {
            best = theta;
            branch = i;
          }
        }
      }
      if (is_bandit(u.kind)) {
        std::vector<double> means(u.n_branches);
        for (int i = 0; i < u.n_branches; ++i)
          means[i] = u.reward_sum[i] / (double)(u.pulls[i] ? u.pulls[i] : 1);
        out.bandit_tags.push_back({idx, std::move(means)});
      }
      if (branch >= (int)u.children.size()) {
        out.err_code = 500;
        out.err_reason = "BAD_ROUTING";
        out.err_info = "router returned branch outside children";
        return false;
      }
      out.routing.push_back({u.name, branch});
      if (!eval_unit(prog, u.children[branch], rng, in, out, result, owner))
        return false;
      out.path.push_back({u.name, kind_class(u.kind)});
      return true;
    }
    case Kind::AverageCombiner: {
      if (in.kind == PKind::Str || in.kind == PKind::Bin || in.kind == PKind::Json) {
        out.err_code = 500;
        out.err_reason = "INTERNAL_ERROR";
        out.err_info = "AverageCombiner requires numeric child outputs";
        return false;
      }
      Payload merged;
      Kind sub_owner;
      for (size_t i = 0; i < u.children.size(); ++i) {
        Payload child_out;
        if (!eval_unit(prog, u.children[i], rng, in, out, child_out, sub_owner))
          return false;
        if (i == 0) merged = child_out;
        else if (child_out.rows != merged.rows) {
          out.err_code = 500;
          out.err_reason = "INTERNAL_ERROR";
          out.err_info = "AverageCombiner inputs must share a shape";
          return false;
        }
      }
      if (u.children.empty()) merged = in;
      out.path.push_back({u.name, kind_class(u.kind)});
      result = merged;
      owner = Kind::AverageCombiner;
      return true;
    }
  }
  return false;
}

// ---------------------------------------------------------------------------
// Protobuf wire helpers (hand-rolled; schema = proto/prediction.proto)
// ---------------------------------------------------------------------------

struct PbReader {
  const uint8_t* p;
  const uint8_t* end;

  bool varint(uint64_t& out) {
    out = 0;
    int shift = 0;
    while (p < end) {
      uint8_t b = *p++;
      out |= (uint64_t)(b & 0x7f) << shift;
      if (!(b & 0x80)) return true;
      shift += 7;
      if (shift > 63) return false;
    }
    return false;
  }
  bool tag(uint32_t& field, uint32_t& wire) {
    if (p >= end) return false;
    uint64_t t;
    if (!varint(t)) return false;
    field = (uint32_t)(t >> 3);
    wire = (uint32_t)(t & 7);
    return true;
  }
  bool len_span(std::string_view& out) {
    uint64_t len;
    if (!varint(len)) return false;
    if ((uint64_t)(end - p) < len) return false;
    out = {(const char*)p, (size_t)len};
    p += len;
    return true;
  }
  bool skip(uint32_t wire) {
    uint64_t tmp;
    std::string_view sv;
    switch (wire) {
      case 0: return varint(tmp);
      case 1: if (end - p < 8) return false; p += 8; return true;
      case 2: return len_span(sv);
      case 5: if (end - p < 4) return false; p += 4; return true;
      default: return false;
    }
  }
};

struct PbWriter {
  Buf& b;
  void varint(uint64_t v) {
    while (v >= 0x80) {
      b.push((char)(v | 0x80));
      v >>= 7;
    }
    b.push((char)v);
  }
  void tag(uint32_t field, uint32_t wire) { varint((uint64_t)field << 3 | wire); }
  void str(uint32_t field, std::string_view s) {
    tag(field, 2);
    varint(s.size());
    b.append(s);
  }
  void fixed32(uint32_t field, float v) {
    tag(field, 5);
    b.append((const char*)&v, 4);
  }
  void fixed64_raw(double v) { b.append((const char*)&v, 8); }
};

// Parsed gRPC SeldonMessage request (spans into the request buffer).
struct PbSeldonMsg {
  Payload in;
  std::string_view puid;
  std::vector<std::string_view> meta_echo;  // raw Meta fields 2/3/4/5 (tag+len+payload)
  std::vector<std::string_view> req_metrics_raw;  // Meta field 5 entries
  int64_t tensor_prod = -1, tensor_nvals = -1;
  // device graphs: actual tensor contents (want_values) + names presence
  bool want_values = false;
  bool has_names = false;
  std::vector<uint32_t> dims;
  std::vector<double> vals;
  const char* err = nullptr;
};

// Parse a Meta submessage (echo spans + puid).
bool pb_parse_meta(std::string_view span, PbSeldonMsg& out) {
  PbReader r{(const uint8_t*)span.data(), (const uint8_t*)span.data() + span.size()};
  while (r.p < r.end) {
    const uint8_t* field_start = r.p;
    uint32_t field, wire;
    if (!r.tag(field, wire)) return false;
    if (field == 1 && wire == 2) {
      if (!r.len_span(out.puid)) return false;
    } else if ((field >= 2 && field <= 5) && wire == 2) {
      std::string_view sv;
      if (!r.len_span(sv)) return false;
      std::string_view full{(const char*)field_start, (size_t)(r.p - field_start)};
      if (field == 5) out.req_metrics_raw.push_back(full);
      else out.meta_echo.push_back(full);
    } else {
      if (!r.skip(wire)) return false;
    }
  }
  return true;
}

// ListValue rows: count of top-level Value elements; 2-D iff first is a list.
bool pb_listvalue_rows(std::string_view span, int64_t& rows) {
  PbReader r{(const uint8_t*)span.data(), (const uint8_t*)span.data() + span.size()};
  int64_t count = 0;
  bool first_is_list = false;
  while (r.p < r.end) {
    uint32_t field, wire;
    if (!r.tag(field, wire)) return false;
    if (field == 1 && wire == 2) {
      std::string_view value_span;
      if (!r.len_span(value_span)) return false;
      if (count == 0) {
        PbReader vr{(const uint8_t*)value_span.data(),
                    (const uint8_t*)value_span.data() + value_span.size()};
        uint32_t vf, vw;
        if (vr.tag(vf, vw)) first_is_list = (vf == 6);
      }
      ++count;
    } else if (!r.skip(wire)) {
      return false;
    }
  }
  rows = first_is_list ? count : (count > 0 ? 1 : 0);
  return true;
}

bool pb_parse_tensor(std::string_view span, PbSeldonMsg& out) {
  PbReader r{(const uint8_t*)span.data(), (const uint8_t*)span.data() + span.size()};
  int64_t prod = 1, rows = 1, nvals = 0, ndims = 0;
  while (r.p < r.end) {
    uint32_t field, wire;
    if (!r.tag(field, wire)) return false;
    if (field == 1 && wire == 2) {  // packed shape
      std::string_view sv;
      if (!r.len_span(sv)) return false;
      PbReader sr{(const uint8_t*)sv.data(), (const uint8_t*)sv.data() + sv.size()};
      uint64_t d;
      while (sr.p < sr.end && sr.varint(d)) {
        if (ndims == 0) rows = (int64_t)d;
        prod *= (int64_t)d;
        ++ndims;
        if (out.want_values) out.dims.push_back((uint32_t)d);
      }
    } else if (field == 1 && wire == 0) {  // unpacked shape element
      uint64_t d;
      if (!r.varint(d)) return false;
      if (ndims == 0) rows = (int64_t)d;
      prod *= (int64_t)d;
      ++ndims;
      if (out.want_values) out.dims.push_back((uint32_t)d);
    } else if (field == 2 && wire == 2) {  // packed doubles
      std::string_view sv;
      if (!r.len_span(sv)) return false;
      nvals += (int64_t)(sv.size() / 8);
      if (out.want_values) {
        size_t n = sv.size() / 8;
        size_t base = out.vals.size();
        out.vals.resize(base + n);
        memcpy(out.vals.data() + base, sv.data(), n * 8);
      }
    } else if (field == 2 && wire == 1) {
      if (out.want_values) {
        if (r.end - r.p < 8) return false;
        double v;
        memcpy(&v, r.p, 8);
        out.vals.push_back(v);
      }
      if (!r.skip(wire)) return false;
      ++nvals;
    } else if (!r.skip(wire)) {
      return false;
    }
  }
  if (ndims == 0) {
    prod = nvals;
    rows = 1;
  }
  out.tensor_prod = prod;
  out.tensor_nvals = nvals;
  out.in.kind = PKind::Tensor;
  out.in.rows = ndims >= 2 ? rows : 1;
  return true;
}

bool pb_parse_seldon_message(std::string_view msg, PbSeldonMsg& out) {
  PbReader r{(const uint8_t*)msg.data(), (const uint8_t*)msg.data() + msg.size()};
  while (r.p < r.end) {
    uint32_t field, wire;
    if (!r.tag(field, wire)) return false;
    if (field == 2 && wire == 2) {  // meta
      std::string_view sv;
      if (!r.len_span(sv)) return false;
      if (!pb_parse_meta(sv, out)) return false;
    } else if (field == 3 && wire == 2) {  // DefaultData
      std::string_view data_span;
      if (!r.len_span(data_span)) return false;
      PbReader dr{(const uint8_t*)data_span.data(),
                  (const uint8_t*)data_span.data() + data_span.size()};
      while (dr.p < dr.end) {
        uint32_t df, dw;
        if (!dr.tag(df, dw)) return false;
        if (df == 1 && dw == 2) {  // names (device graphs fall back on these)
          out.has_names = true;
          if (!dr.skip(dw)) return false;
        } else if (df == 2 && dw == 2) {
          std::string_view tspan;
          if (!dr.len_span(tspan)) return false;
          if (!pb_parse_tensor(tspan, out)) return false;
        } else if (df == 3 && dw == 2) {
          std::string_view nd;
          if (!dr.len_span(nd)) return false;
          out.in.kind = PKind::NDArray;
          if (!pb_listvalue_rows(nd, out.in.rows)) return false;
        } else if (!dr.skip(dw)) {
          return false;
        }
      }
    } else if (field == 4 && wire == 2) {
      if (!r.len_span(out.in.echo)) return false;
      out.in.kind = PKind::Bin;
    } else if (field == 5 && wire == 2) {
      if (!r.len_span(out.in.echo)) return false;
      out.in.kind = PKind::Str;
    } else if (field == 6 && wire == 2) {
      std::string_view sv;
      if (!r.len_span(sv)) return false;
      out.in.kind = PKind::Json;
    } else if (!r.skip(wire)) {
      return false;
    }
  }
  return true;
}

// ---------------------------------------------------------------------------
// Device-graph execution: graphs mixing builtin units with DEVICE_MODEL
// leaves. The edge evaluates routing/combining natively and ships each
// model leaf's input tensor to the engine process (ring kind 2); payload
// values flow as real numbers (the stub path above never materialises them).
// ---------------------------------------------------------------------------

// bf16-era float32 constants of the SimpleModel stub, as python floats
constexpr double kStubVals[3] = {(double)0.1f, (double)0.9f, (double)0.5f};

struct DVal {
  enum T { Resolved, Site, Avg } t = Resolved;
  std::vector<double> vals;
  std::vector<uint32_t> dims;
  uint8_t dtype = 0;  // 0=f32, 1=f64 — np.mean parity needs the math dtype
  int site = -1;      // t==Site: index into DevExec::sites
  std::vector<DVal> ch;  // t==Avg
};

struct DevSite {
  int unit_idx = -1;
  uint32_t req_id = 0;
  uint8_t method = 0;    // 0 = predict, 1 = transform_input
  int input_site = -1;   // >=0: input is that site's output (deferred push)
  bool issued = false;
  bool owns_pending = false;  // inserted into pending_dev under req_id
  bool chain_member = false;  // carried inside an upstream site's frame
  std::vector<int> chain;     // fused downstream stages (in order)
  bool done = false;
  // request tensor (shipped) and response tensor (filled by drain)
  std::vector<uint32_t> req_dims;
  std::vector<double> req_vals;
  std::vector<uint32_t> dims;
  std::vector<double> vals;
  uint8_t dtype = 0;
  std::string fragment;  // executor JSON: {"names":[...],"tags":{},"metrics":[...]}
};

// Per-traversal-order metric source: a builtin stub visit or a device site.
struct MetricSrc {
  int site = -1;  // -1 => builtin SimpleModel constants
};

struct DevExec {
  int conn_fd = -1;
  uint32_t conn_gen = 0;
  uint64_t t0 = 0;
  bool is_grpc = false;   // response goes out as proto on h2_sid
  uint32_t h2_sid = 0;
  std::string body;  // request copy: doc's/proto spans point into this
  JDoc doc;          // REST: parsed ONCE over body; survives the park
  PbSeldonMsg preq;  // gRPC: ditto (meta echo spans into body)
  ExecOut ex;
  DVal result;
  std::vector<DevSite> sites;
  std::vector<MetricSrc> metric_srcs;  // traversal order
  int outstanding = 0;
  Kind owner = Kind::SimpleModel;
  int owner_site = -1;   // owner==DeviceModel: which site names the payload
  PKind resp_kind = PKind::NDArray;
};

// Recursive eval for device graphs. Routing/bandit logic deliberately
// mirrors eval_unit above (the stub path); divergence between the two is
// covered by the randomized parity fuzz in tests/test_edge.py.
bool eval_device(const Program& prog, int idx, Rng& rng, const DVal& in,
                 ExecOut& out, std::vector<DevSite>& sites,
                 std::vector<MetricSrc>& metric_srcs, DVal& result,
                 Kind& owner, int& owner_site) {
  const Unit& u = prog.units[idx];
  switch (u.kind) {
    case Kind::DeviceModel: {
      DevSite site;
      site.unit_idx = idx;
      if (in.t == DVal::Site) {
        site.input_site = in.site;  // upstream transform feeds this call
      } else {
        site.req_dims = in.dims;
        site.req_vals = in.vals;
      }
      sites.push_back(std::move(site));
      metric_srcs.push_back({(int)sites.size() - 1});
      result = DVal{};
      result.t = DVal::Site;
      result.site = (int)sites.size() - 1;
      owner = Kind::DeviceModel;
      owner_site = result.site;
      out.path.push_back({u.name, u.class_name.c_str()});
      return true;
    }
    case Kind::DeviceTransform: {
      // input transformer: ring call produces the child's input
      DevSite site;
      site.unit_idx = idx;
      site.method = 1;
      if (in.t == DVal::Site) site.input_site = in.site;
      else {
        site.req_dims = in.dims;
        site.req_vals = in.vals;
      }
      sites.push_back(std::move(site));
      int my_site = (int)sites.size() - 1;
      metric_srcs.push_back({my_site});
      DVal mine;
      mine.t = DVal::Site;
      mine.site = my_site;
      if (u.children.empty()) {
        out.path.push_back({u.name, u.class_name.c_str()});
        result = std::move(mine);
        owner = Kind::DeviceModel;  // names come from this site's fragment
        owner_site = my_site;
        return true;
      }
      Kind sub_owner = Kind::SimpleModel;
      int sub_site = -1;
      DVal final_out;
      if (!eval_device(prog, u.children[0], rng, mine, out, sites,
                       metric_srcs, final_out, sub_owner, sub_site))
        return false;
      out.path.push_back({u.name, u.class_name.c_str()});
      result = std::move(final_out);
      owner = sub_owner;
      owner_site = sub_site;
      return true;
    }
    case Kind::SimpleModel: {
      int64_t rows = in.dims.size() >= 2 ? in.dims[0] : 1;
      DVal mine;
      mine.dims = {(uint32_t)rows, 3};
      mine.vals.reserve(rows * 3);
      for (int64_t r = 0; r < rows; ++r)
        for (double v : kStubVals) mine.vals.push_back(v);
      ++out.model_visits;
      metric_srcs.push_back({-1});
      Kind sub_owner = Kind::SimpleModel;
      int sub_site = -1;
      DVal final_out = mine;
      if (!u.children.empty()) {
        if (!eval_device(prog, u.children[0], rng, mine, out, sites,
                         metric_srcs, final_out, sub_owner, sub_site))
          return false;
      }
      out.path.push_back({u.name, kind_class(u.kind)});
      result = std::move(final_out);
      owner = u.children.empty() ? Kind::SimpleModel : sub_owner;
      owner_site = u.children.empty() ? -1 : sub_site;
      return true;
    }
    case Kind::SimpleRouter:
    case Kind::RandomABTest:
    case Kind::EpsilonGreedy:
    case Kind::ThompsonSampling: {
      int branch = 0;
      if (u.kind == Kind::RandomABTest) {
        if (u.py_rng) {  // seeded: CPython random.Random replay
          branch = u.n_branches == 2
                       ? (u.py_rng->random() < u.ratioA ? 0 : 1)
                       : (int)u.py_rng->randrange((uint64_t)u.n_branches);
        } else if (u.n_branches == 2)
          branch = rng.uniform() < u.ratioA ? 0 : 1;
        else
          branch = (int)(rng.uniform() * u.n_branches) % u.n_branches;
      } else if (u.kind == Kind::EpsilonGreedy) {
        uint64_t total = 0;
        for (uint64_t p : u.pulls) total += p;
        double eps_draw = u.np_rng ? u.np_rng->random() : rng.uniform();
        if (eps_draw < u.epsilon) {
          branch = u.np_rng ? (int)u.np_rng->integers((uint64_t)u.n_branches)
                            : (int)(rng.next() % (uint64_t)u.n_branches);
        } else if (total == 0) {
          branch = u.best_branch;
        } else {
          double best = -1.0;
          for (int i = 0; i < u.n_branches; ++i) {
            double mean = u.reward_sum[i] / (double)(u.pulls[i] ? u.pulls[i] : 1);
            if (mean > best) {
              best = mean;
              branch = i;
            }
          }
        }
      } else if (u.kind == Kind::ThompsonSampling) {
        // theta_i ~ Beta(alpha0 + reward_i, beta0 + fail_i), argmax;
        // seeded units replay Generator.beta's elementwise array draw
        // (np_rng.h random_beta) so routing matches the Python engine
        // request-for-request
        double best = -1.0;
        for (int i = 0; i < u.n_branches; ++i) {
          double a = u.alpha0 + u.reward_sum[i], b = u.beta0 + u.fail_sum[i];
          double theta = u.np_rng ? u.np_rng->beta(a, b) : rng.beta(a, b);
          if (theta > best) {
            best = theta;
            branch = i;
          }
        }
      }
      if (is_bandit(u.kind)) {
        std::vector<double> means(u.n_branches);
        for (int i = 0; i < u.n_branches; ++i)
          means[i] = u.reward_sum[i] / (double)(u.pulls[i] ? u.pulls[i] : 1);
        out.bandit_tags.push_back({idx, std::move(means)});
      }
      if (branch >= (int)u.children.size()) {
        out.err_code = 500;
        out.err_reason = "BAD_ROUTING";
        out.err_info = "router returned branch outside children";
        return false;
      }
      out.routing.push_back({u.name, branch});
      if (!eval_device(prog, u.children[branch], rng, in, out, sites,
                       metric_srcs, result, owner, owner_site))
        return false;
      out.path.push_back({u.name, kind_class(u.kind)});
      return true;
    }
    case Kind::AverageCombiner: {
      DVal merged;
      merged.t = DVal::Avg;
      Kind sub_owner;
      int sub_site;
      for (size_t i = 0; i < u.children.size(); ++i) {
        DVal child_out;
        if (!eval_device(prog, u.children[i], rng, in, out, sites,
                         metric_srcs, child_out, sub_owner, sub_site))
          return false;
        merged.ch.push_back(std::move(child_out));
      }
      if (u.children.empty()) merged = in;
      out.path.push_back({u.name, kind_class(u.kind)});
      result = std::move(merged);
      owner = Kind::AverageCombiner;
      owner_site = -1;
      return true;
    }
  }
  return false;
}

// Resolve the dataflow tree once every site's response landed. np.mean
// parity: all-f32 children accumulate in f32, any f64 promotes the math.
bool resolve_dval(const DVal& v, const std::vector<DevSite>& sites,
                  std::vector<double>& vals, std::vector<uint32_t>& dims,
                  uint8_t& dtype, std::string& err) {
  switch (v.t) {
    case DVal::Resolved:
      vals = v.vals;
      dims = v.dims;
      dtype = v.dtype;
      return true;
    case DVal::Site:
      vals = sites[v.site].vals;
      dims = sites[v.site].dims;
      dtype = sites[v.site].dtype;
      return true;
    case DVal::Avg: {
      if (v.ch.empty()) {
        err = "AverageCombiner requires children";
        return false;
      }
      std::vector<std::vector<double>> child_vals(v.ch.size());
      uint8_t promote = 0;
      for (size_t i = 0; i < v.ch.size(); ++i) {
        std::vector<uint32_t> cdims;
        uint8_t cdtype;
        if (!resolve_dval(v.ch[i], sites, child_vals[i], cdims, cdtype, err))
          return false;
        if (i == 0) dims = cdims;
        else if (cdims != dims) {
          err = "AverageCombiner inputs must share a shape";
          return false;
        }
        if (cdtype) promote = 1;
      }
      dtype = promote;
      size_t n = child_vals[0].size();
      vals.assign(n, 0.0);
      for (size_t e = 0; e < n; ++e) {
        if (promote) {
          double acc = 0.0;
          for (auto& cv : child_vals) acc += cv[e];
          vals[e] = acc / (double)child_vals.size();
        } else {
          float acc = 0.0f;
          for (auto& cv : child_vals) acc += (float)cv[e];
          vals[e] = (double)(acc / (float)child_vals.size());
        }
      }
      return true;
    }
  }
  return false;
}

// ---------------------------------------------------------------------------
// HPACK (RFC 7541) — decoder without Huffman. grpc-c encodes header literals
// raw (verified against the grpcio in this image); a Huffman-coded :path is
// rejected with a stream error rather than misrouted.
// ---------------------------------------------------------------------------

static const char* kHpackStatic[62][2] = {
    {"", ""},  // 1-based
    {":authority", ""}, {":method", "GET"}, {":method", "POST"}, {":path", "/"},
    {":path", "/index.html"}, {":scheme", "http"}, {":scheme", "https"},
    {":status", "200"}, {":status", "204"}, {":status", "206"},
    {":status", "304"}, {":status", "400"}, {":status", "404"},
    {":status", "500"}, {"accept-charset", ""},
    {"accept-encoding", "gzip, deflate"}, {"accept-language", ""},
    {"accept-ranges", ""}, {"accept", ""}, {"access-control-allow-origin", ""},
    {"age", ""}, {"allow", ""}, {"authorization", ""}, {"cache-control", ""},
    {"content-disposition", ""}, {"content-encoding", ""},
    {"content-language", ""}, {"content-length", ""}, {"content-location", ""},
    {"content-range", ""}, {"content-type", ""}, {"cookie", ""}, {"date", ""},
    {"etag", ""}, {"expect", ""}, {"expires", ""}, {"from", ""}, {"host", ""},
    {"if-match", ""}, {"if-modified-since", ""}, {"if-none-match", ""},
    {"if-range", ""}, {"if-unmodified-since", ""}, {"last-modified", ""},
    {"link", ""}, {"location", ""}, {"max-forwards", ""},
    {"proxy-authenticate", ""}, {"proxy-authorization", ""}, {"range", ""},
    {"referer", ""}, {"refresh", ""}, {"retry-after", ""}, {"server", ""},
    {"set-cookie", ""}, {"strict-transport-security", ""},
    {"transfer-encoding", ""}, {"user-agent", ""}, {"vary", ""}, {"via", ""},
    {"www-authenticate", ""},
};
constexpr uint64_t kHpackStaticCount = 61;

struct HpackDyn {
  std::vector<std::pair<std::string, std::string>> entries;  // front = newest
  size_t bytes = 0;
  size_t cap = 4096;

  void add(std::string name, std::string value) {
    size_t sz = name.size() + value.size() + 32;
    entries.insert(entries.begin(), {std::move(name), std::move(value)});
    bytes += sz;
    evict();
  }
  void set_cap(size_t c) {
    cap = c;
    evict();
  }
  void evict() {
    while (bytes > cap && !entries.empty()) {
      auto& e = entries.back();
      bytes -= e.first.size() + e.second.size() + 32;
      entries.pop_back();
    }
  }
  bool get(uint64_t idx, std::string& name, std::string& value) const {
    if (idx >= 1 && idx <= kHpackStaticCount) {
      name = kHpackStatic[idx][0];
      value = kHpackStatic[idx][1];
      return true;
    }
    uint64_t d = idx - kHpackStaticCount - 1;
    if (d < entries.size()) {
      name = entries[d].first;
      value = entries[d].second;
      return true;
    }
    return false;
  }
};

bool hpack_int(const uint8_t*& p, const uint8_t* end, int prefix, uint64_t& out) {
  if (p >= end) return false;
  uint64_t max_prefix = (1u << prefix) - 1;
  out = *p & max_prefix;
  ++p;
  if (out < max_prefix) return true;
  int shift = 0;
  while (p < end) {
    uint8_t b = *p++;
    out += (uint64_t)(b & 0x7f) << shift;
    if (!(b & 0x80)) return true;
    shift += 7;
    if (shift > 56) return false;
  }
  return false;
}

// Decoded field; value_huffman marks values we could not decode.
struct HpackField {
  std::string name, value;
  bool value_huffman = false;
};

bool hpack_string(const uint8_t*& p, const uint8_t* end, std::string& out,
                  bool& huffman) {
  if (p >= end) return false;
  huffman = (*p & 0x80) != 0;
  uint64_t len;
  if (!hpack_int(p, end, 7, len)) return false;
  if ((uint64_t)(end - p) < len) return false;
  out.assign((const char*)p, len);  // raw bytes (encoded if huffman)
  p += len;
  return true;
}

bool hpack_decode(const uint8_t* p, const uint8_t* end, HpackDyn& dyn,
                  std::vector<HpackField>& out) {
  while (p < end) {
    uint8_t b = *p;
    if (b & 0x80) {  // indexed
      uint64_t idx;
      if (!hpack_int(p, end, 7, idx)) return false;
      HpackField f;
      if (!dyn.get(idx, f.name, f.value)) return false;
      out.push_back(std::move(f));
    } else if ((b & 0xc0) == 0x40) {  // literal, incremental indexing
      uint64_t idx;
      if (!hpack_int(p, end, 6, idx)) return false;
      HpackField f;
      bool name_huff = false;
      if (idx == 0) {
        if (!hpack_string(p, end, f.name, name_huff)) return false;
      } else {
        std::string v;
        if (!dyn.get(idx, f.name, v)) return false;
      }
      if (!hpack_string(p, end, f.value, f.value_huffman)) return false;
      // Huffman-coded strings are stored encoded; an indexed re-reference
      // yields the same bytes, so matching stays consistent without a
      // Huffman decoder (we only ever *compare* values, never display them).
      (void)name_huff;
      dyn.add(f.name, f.value);
      out.push_back(std::move(f));
    } else if ((b & 0xe0) == 0x20) {  // dynamic table size update
      uint64_t cap;
      if (!hpack_int(p, end, 5, cap)) return false;
      dyn.set_cap(cap);
    } else {  // literal without indexing / never indexed (prefix 4 bits)
      uint64_t idx;
      if (!hpack_int(p, end, 4, idx)) return false;
      HpackField f;
      bool name_huff = false;
      if (idx == 0) {
        if (!hpack_string(p, end, f.name, name_huff)) return false;
      } else {
        std::string v;
        if (!dyn.get(idx, f.name, v)) return false;
      }
      if (!hpack_string(p, end, f.value, f.value_huffman)) return false;
      out.push_back(std::move(f));
    }
  }
  return true;
}

// ---------------------------------------------------------------------------
// HTTP layer
// ---------------------------------------------------------------------------

struct RingPending {
  int conn_fd;
  uint32_t conn_gen;  // guards against kernel fd-number reuse
  uint64_t started_ns;
  bool is_feedback;
};

struct H2Stream {
  std::string path;
  Buf data;
  uint32_t recv_unacked = 0;  // bytes received since the last stream-level grant
  bool path_huffman = false;
};

// A response whose DATA has not fully cleared flow control: remaining gRPC
// message bytes (unframed — frames are cut at send time so they respect the
// peer's SETTINGS_MAX_FRAME_SIZE) plus this stream's remaining send window.
struct H2Blocked {
  uint32_t sid;
  std::string data;
  size_t off = 0;
  int64_t stream_window = 65535;
};

struct H2State {
  HpackDyn hpack;
  std::unordered_map<uint32_t, H2Stream> streams;
  int64_t send_window = 65535;            // connection-level send window
  int64_t client_initial_window = 65535;  // SETTINGS_INITIAL_WINDOW_SIZE
  uint32_t client_max_frame = 16384;      // SETTINGS_MAX_FRAME_SIZE
  uint32_t recv_unacked = 0;
  std::deque<H2Blocked> blocked;  // responses awaiting window
  // WINDOW_UPDATE credit granted before the response was queued (e.g. a
  // client using SETTINGS_INITIAL_WINDOW_SIZE=0 + explicit grants).
  std::unordered_map<uint32_t, int64_t> stream_credit;
};

struct Conn {
  int fd = -1;
  uint32_t gen = 0;  // bumped on close so stale ring responses can't match
  Buf in;
  Buf outbuf;
  size_t out_off = 0;
  bool want_close = false;
  bool waiting_ring = false;  // response will come from the ring
  bool is_h2 = false;
  bool flush_pending = false;  // queued for one coalesced flush at the end
                               // of the current ring-drain pass
  std::unique_ptr<H2State> h2;
};

uint64_t now_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + ts.tv_nsec;
}

struct Server {
  // request-body ceiling for both protocols (aiohttp client_max_size parity
  // on HTTP/1; per-stream buffer cap on HTTP/2)
  static constexpr size_t kMaxBody = 1u << 30;
  Program prog;
  Metrics metrics;
  Rng rng;
  bool paused = false;
  size_t max_inflight = 4096;  // overload-shed threshold (--max-inflight)
  std::string openapi;  // served at /seldon.json when provided

  // ring fallback
  void* req_ring = nullptr;
  void* resp_ring = nullptr;
  uint32_t ring_slot = 0;
  uint32_t next_req_id = 1;
  std::unordered_map<uint32_t, RingPending> pending;
  // device-graph requests: one entry per outstanding model call
  std::unordered_map<uint32_t, std::pair<DevExec*, int>> pending_dev;
  // gRPC streams parked on a full-proto ring round-trip (kind 3)
  struct GrpcPending {
    int conn_fd;
    uint32_t conn_gen;
    uint32_t sid;
    uint64_t started_ns;
    bool is_feedback;
  };
  std::unordered_map<uint32_t, GrpcPending> pending_grpc;
  uint16_t ring_worker_id = 0;
  std::vector<char> ring_buf;  // reused drain buffer (slot-sized)
  bool defer_flush = false;    // drain pass active: flush_out queues instead
  std::vector<int> flush_queue;
  static constexpr uint64_t kRingTimeoutNs = 30ull * 1000000000ull;

  std::vector<Conn> conns;
  int epfd = -1;
  int timer_fd = -1;
  bool timer_armed = false;

  Conn& conn(int fd) {
    if ((size_t)fd >= conns.size()) conns.resize(fd + 1);
    return conns[(size_t)fd];
  }

  // ---- response helpers ----
  void http_head(Buf& b, int code, const char* text, size_t body_len,
                 const char* ctype, bool close_conn) {
    b.append("HTTP/1.1 ");
    b.append_i64(code);
    b.push(' ');
    b.append(text);
    b.append("\r\nContent-Type: ");
    b.append(ctype);
    b.append("\r\nContent-Length: ");
    b.append_u64(body_len);
    if (close_conn) b.append("\r\nConnection: close");
    b.append("\r\n\r\n");
  }
  void respond(Conn& c, int code, const char* text, std::string_view body,
               const char* ctype = "application/json; charset=utf-8") {
    http_head(c.outbuf, code, text, body.size(), ctype, c.want_close);
    c.outbuf.append(body);
  }
  void respond_error(Conn& c, int code, const char* reason, std::string_view info) {
    Buf body;
    body.append("{\"status\": {\"code\": ");
    body.append_i64(code);
    body.append(", \"info\": \"");
    body.append_json_escaped(info);
    body.append("\", \"reason\": \"");
    body.append(reason);
    body.append("\", \"status\": \"FAILURE\"}}");
    const char* text = code == 400 ? "Bad Request"
                       : code == 404 ? "Not Found"
                       : code == 405 ? "Method Not Allowed"
                       : code == 413 ? "Payload Too Large"
                       : code == 429 ? "Too Many Requests"
                       : code == 503 ? "Service Unavailable"
                       : code == 504 ? "Gateway Timeout"
                                     : "Internal Server Error";
    respond(c, code, text, {body.data(), body.size()});
  }

  // ---- overload shed ----
  // Deterministic load-shed past the knee (the reference degrades via
  // bounded Tomcat pools, RestClientController.java:120-132; the edge's
  // equivalent is a bound on parked in-flight work). When the total parked
  // population reaches --max-inflight, new predictions get an immediate
  // HTTP 429 / gRPC RESOURCE_EXHAUSTED instead of joining a queue that can
  // only grow — responses stay well-formed at any offered load, and the
  // shed count is visible in /metrics (seldon_edge_shed_total).
  bool overloaded() const {
    return pending.size() + pending_dev.size() + pending_grpc.size() >=
           max_inflight;
  }
  void shed_http(Conn& c, uint64_t t0) {
    ++metrics.shed_total;
    respond_error(c, 429, "RESOURCE_EXHAUSTED",
                  "in-flight request limit reached; retry later");
    metrics.observe_api("predictions", 429, 1e-9 * (now_ns() - t0));
  }

  // ---- predictions ----
  void handle_predictions(Conn& c, std::string_view body, uint64_t t0) {
    if (paused) {
      respond(c, 503, "Service Unavailable",
              "{\"status\": {\"code\": 503, \"info\": \"paused\", \"status\": \"FAILURE\"}}");
      metrics.observe_api("predictions", 503, 1e-9 * (now_ns() - t0));
      return;
    }
    if (overloaded()) {
      shed_http(c, t0);
      return;
    }
    if (!prog.native) {
      forward_ring(c, 0, body, t0);
      return;
    }
    if (prog.has_device) {
      // device graphs own their parse: the doc must outlive the park, so it
      // is built once over the DevExec's body copy (no re-parse at finish)
      handle_predictions_device(c, body, t0);
      return;
    }
    JDoc doc;
    if (!json_parse(body.data(), body.size(), doc)) {
      std::string info = std::string("Invalid JSON body: ") + (doc.err ? doc.err : "parse error");
      respond_error(c, 400, "MICROSERVICE_BAD_DATA", info);
      metrics.observe_api("predictions", 400, 1e-9 * (now_ns() - t0));
      return;
    }
    const JValue& root = doc.nodes[0];
    if (root.type != JValue::Obj) {
      respond_error(c, 400, "MICROSERVICE_BAD_DATA", "request must be a JSON object");
      metrics.observe_api("predictions", 400, 1e-9 * (now_ns() - t0));
      return;
    }

    // --- decode request payload ---
    Payload in;
    const JValue* data = doc.get(root, "data");
    const JValue* strData = doc.get(root, "strData");
    const JValue* binData = doc.get(root, "binData");
    const JValue* jsonData = doc.get(root, "jsonData");
    const JValue* tensor = nullptr;
    if (data && data->type == JValue::Obj) {
      if (auto* nd = doc.get(*data, "ndarray")) {
        in.kind = PKind::NDArray;
        if (nd->type != JValue::Arr) {
          respond_error(c, 400, "MICROSERVICE_BAD_DATA", "ndarray must be an array");
          metrics.observe_api("predictions", 400, 1e-9 * (now_ns() - t0));
          return;
        }
        // rows = len(ndarray) if 2-D else 1
        bool two_d = nd->n_children > 0 && doc.item(*nd, 0)->type == JValue::Arr;
        in.rows = two_d ? nd->n_children : 1;
      } else if ((tensor = doc.get(*data, "tensor"))) {
        in.kind = PKind::Tensor;
        const JValue* shape = doc.get(*tensor, "shape");
        const JValue* values = doc.get(*tensor, "values");
        int64_t prod = 1, r = 1;
        if (shape && shape->type == JValue::Arr && shape->n_children > 0) {
          for (int i = 0; i < shape->n_children; ++i) {
            int64_t d = (int64_t)jnum(*doc.item(*shape, i));
            prod *= d;
            if (i == 0) r = d;
          }
        } else {
          r = 1;
          prod = values ? values->n_children : 0;
        }
        int64_t nvals = values ? values->n_children : 0;
        if (prod != nvals) {
          char msg[128];
          snprintf(msg, sizeof(msg), "tensor values do not fit shape: %" PRId64
                   " values for %" PRId64 " elements", nvals, prod);
          respond_error(c, 400, "MICROSERVICE_BAD_DATA", msg);
          metrics.observe_api("predictions", 400, 1e-9 * (now_ns() - t0));
          return;
        }
        in.rows = shape && shape->n_children >= 2 ? r : 1;
      }
    } else if (strData) {
      in.kind = PKind::Str;
      in.echo = strData->sv;
    } else if (binData) {
      in.kind = PKind::Bin;
      in.echo = binData->sv;
    } else if (jsonData) {
      in.kind = PKind::Json;
    }

    // --- run the graph ---
    ExecOut ex;
    Payload result;
    Kind owner;
    if (!eval_unit(prog, prog.root, rng, in, ex, result, owner)) {
      respond_error(c, ex.err_code, ex.err_reason, ex.err_info);
      metrics.observe_api("predictions", ex.err_code, 1e-9 * (now_ns() - t0));
      return;
    }

    // --- response meta ---
    const JValue* meta = doc.get(root, "meta");
    std::string_view req_puid;
    const JValue* req_tags = nullptr;
    const JValue* req_routing = nullptr;
    const JValue* req_path = nullptr;
    const JValue* req_metrics = nullptr;
    if (meta && meta->type == JValue::Obj) {
      if (auto* v = doc.get(*meta, "puid")) req_puid = v->sv;
      if (auto* v = doc.get(*meta, "tags")) req_tags = v;
      if (auto* v = doc.get(*meta, "routing")) req_routing = v;
      if (auto* v = doc.get(*meta, "requestPath")) req_path = v;
      if (auto* v = doc.get(*meta, "metrics")) req_metrics = v;
    }
    char puid[33];
    if (req_puid.empty()) rng.puid_hex(puid);

    Buf& b = c.outbuf;
    Buf body_buf;
    body_buf.append("{\"meta\": {\"puid\": \"");
    if (req_puid.empty()) body_buf.append(puid, 32);
    else body_buf.append(req_puid);
    body_buf.push('"');
    // A non-object tags value can't be key-merged (and indexing it as an
    // object would read the wrong parser arena): keep the legacy verbatim
    // echo for it and skip the bandit fragment.
    bool have_bandit = !ex.bandit_tags.empty();
    if (req_tags && req_tags->type != JValue::Obj) {
      if (req_tags->n_children > 0) {
        body_buf.append(", \"tags\": ");
        body_buf.append(req_tags->raw);
      }
    } else if (have_bandit || (req_tags && req_tags->n_children > 0)) {
      // Merged tag dict, Python engine order/precedence (_merge_meta: the
      // router's tags are the source, request tags the target → bandit keys
      // render first but the request's VALUE wins on a key collision).
      body_buf.append(", \"tags\": {");
      bool first = true;
      auto req_tag_value = [&](std::string_view key) -> const JValue* {
        if (!req_tags) return nullptr;
        for (int i = 0; i < req_tags->n_children; ++i) {
          const auto& m = doc.obj_members[req_tags->first_child + i];
          if (m.first == key) return &doc.nodes[m.second];
        }
        return nullptr;
      };
      if (have_bandit) {
        const Unit& bu = prog.units[ex.bandit_tags[0].first];
        body_buf.append("\"bandit\": ");
        if (auto* v = req_tag_value("bandit")) {
          body_buf.append(v->raw);
        } else {
          body_buf.push('"');
          body_buf.append(kind_class(bu.kind));
          body_buf.push('"');
        }
        body_buf.append(", \"branch_means\": ");
        if (auto* v = req_tag_value("branch_means")) {
          body_buf.append(v->raw);
        } else {
          body_buf.push('[');
          const auto& means = ex.bandit_tags[0].second;
          for (size_t i = 0; i < means.size(); ++i) {
            if (i) body_buf.append(", ");
            body_buf.append_double(nearbyint(means[i] * 1e6) / 1e6);  // round(x, 6)
          }
          body_buf.push(']');
        }
        first = false;
      }
      if (req_tags) {
        for (int i = 0; i < req_tags->n_children; ++i) {
          const auto& m = doc.obj_members[req_tags->first_child + i];
          if (have_bandit && (m.first == "bandit" || m.first == "branch_means")) continue;
          if (!first) body_buf.append(", ");
          first = false;
          body_buf.push('"');
          body_buf.append(m.first);
          body_buf.append("\": ");
          body_buf.append(doc.nodes[m.second].raw);
        }
      }
      body_buf.push('}');
    }
    if (!ex.routing.empty() || (req_routing && req_routing->n_children > 0)) {
      body_buf.append(", \"routing\": {");
      bool first = true;
      for (auto& [name, branch] : ex.routing) {
        if (!first) body_buf.append(", ");
        first = false;
        body_buf.push('"');
        body_buf.append(name);
        body_buf.append("\": ");
        body_buf.append_i64(branch);
      }
      if (req_routing) {
        for (int i = 0; i < req_routing->n_children; ++i) {
          const auto& m = doc.obj_members[req_routing->first_child + i];
          bool dup = false;
          for (auto& [name, _] : ex.routing)
            if (name == m.first) dup = true;
          if (dup) continue;
          if (!first) body_buf.append(", ");
          first = false;
          body_buf.push('"');
          body_buf.append(m.first);
          body_buf.append("\": ");
          body_buf.append(doc.nodes[m.second].raw);
        }
      }
      body_buf.push('}');
    }
    body_buf.append(", \"requestPath\": {");
    {
      bool first = true;
      if (req_path) {
        for (int i = 0; i < req_path->n_children; ++i) {
          const auto& m = doc.obj_members[req_path->first_child + i];
          bool dup = false;
          for (auto& [name, _] : ex.path)
            if (name == m.first) dup = true;
          if (dup) continue;
          if (!first) body_buf.append(", ");
          first = false;
          body_buf.push('"');
          body_buf.append(m.first);
          body_buf.append("\": ");
          body_buf.append(doc.nodes[m.second].raw);
        }
      }
      for (auto& [name, cls] : ex.path) {
        if (!first) body_buf.append(", ");
        first = false;
        body_buf.push('"');
        body_buf.append(name);
        body_buf.append("\": \"");
        body_buf.append(cls);
        body_buf.push('"');
      }
    }
    body_buf.push('}');
    if (ex.model_visits > 0 || (req_metrics && req_metrics->n_children > 0)) {
      // Engine merge order (runtime/engine.py _merge_meta + fused path):
      // flow-owner's metrics, then request-carried metrics, then the other
      // executed units' metrics.
      body_buf.append(", \"metrics\": [");
      bool first = true;
      static const char* kModelMetrics =
          "{\"key\": \"mycounter\", \"type\": \"COUNTER\", \"value\": 1.0}, "
          "{\"key\": \"mygauge\", \"type\": \"GAUGE\", \"value\": 100.0}, "
          "{\"key\": \"mytimer\", \"type\": \"TIMER\", \"value\": 20.6}";
      int remaining = ex.model_visits;
      if (owner != Kind::AverageCombiner && remaining > 0) {
        body_buf.append(kModelMetrics);
        first = false;
        --remaining;
      }
      if (req_metrics) {
        for (int i = 0; i < req_metrics->n_children; ++i) {
          if (!first) body_buf.append(", ");
          first = false;
          body_buf.append(doc.item(*req_metrics, i)->raw);
        }
      }
      for (int i = 0; i < remaining; ++i) {
        if (!first) body_buf.append(", ");
        first = false;
        body_buf.append(kModelMetrics);
      }
      body_buf.push(']');
    }
    body_buf.push('}');

    // --- response payload ---
    static const char* kRowVals =
        "0.10000000149011612, 0.8999999761581421, 0.5";
    if (result.kind == PKind::Str) {
      body_buf.append(", \"strData\": \"");
      body_buf.append(result.echo);
      body_buf.push('"');
    } else if (result.kind == PKind::Bin) {
      body_buf.append(", \"binData\": \"");
      body_buf.append(result.echo);
      body_buf.push('"');
    } else if (result.kind == PKind::NDArray || result.kind == PKind::Tensor) {
      body_buf.append(", \"data\": {\"names\": ");
      if (owner == Kind::AverageCombiner)
        body_buf.append("[\"t:0\", \"t:1\", \"t:2\"]");
      else
        body_buf.append("[\"class0\", \"class1\", \"class2\"]");
      if (result.kind == PKind::NDArray) {
        body_buf.append(", \"ndarray\": [");
        for (int64_t r = 0; r < result.rows; ++r) {
          if (r) body_buf.append(", ");
          body_buf.push('[');
          body_buf.append(kRowVals);
          body_buf.push(']');
        }
        body_buf.append("]}");
      } else {
        body_buf.append(", \"tensor\": {\"shape\": [");
        body_buf.append_i64(result.rows);
        body_buf.append(", 3], \"values\": [");
        for (int64_t r = 0; r < result.rows; ++r) {
          if (r) body_buf.append(", ");
          body_buf.append(kRowVals);
        }
        body_buf.append("]}}");
      }
    }
    body_buf.push('}');

    http_head(b, 200, "OK", body_buf.size(), "application/json; charset=utf-8",
              c.want_close);
    b.append(body_buf.data(), body_buf.size());
    // custom metrics as the Python registry would register them
    metrics.mycounter += ex.model_visits;
    if (ex.model_visits) {
      metrics.mygauge = 100.0;
      for (int i = 0; i < ex.model_visits; ++i) metrics.mytimer.observe(20.6 / 1000.0);
      metrics.custom_seen += ex.model_visits;
    }
    metrics.observe_api("predictions", 200, 1e-9 * (now_ns() - t0));
  }

  // Feedback replay down the routed branch (engine._feedback semantics):
  // bandit units whose name appears in response.meta.routing absorb the
  // reward (clamped to [0,1]); descent follows the routed branch only, all
  // children when the unit has no routing entry. Returns false (BAD_ROUTING)
  // when a routing entry names a branch outside the unit's children.
  bool feedback_walk(int idx,
                     const std::vector<std::pair<std::string_view, int>>& routing,
                     double reward) {
    const Unit& u = prog.units[idx];
    int branch = -1;
    for (auto& [name, b] : routing) {
      if (name == u.name) {
        branch = b;
        break;
      }
    }
    if (is_bandit(u.kind) && branch >= 0 && branch < u.n_branches) {
      double r = reward < 0 ? 0.0 : (reward > 1 ? 1.0 : reward);
      u.pulls[branch] += 1;
      u.reward_sum[branch] += r;
      u.fail_sum[branch] += 1.0 - r;
    }
    if (u.children.empty()) return true;
    if (branch == -1) {
      for (int c : u.children)
        if (!feedback_walk(c, routing, reward)) return false;
      return true;
    }
    // engine._feedback: only -1 fans out; anything else outside [0, len)
    // (including other negatives) is BAD_ROUTING
    if (branch < 0 || branch >= (int)u.children.size()) return false;
    return feedback_walk(u.children[branch], routing, reward);
  }

  void handle_feedback(Conn& c, std::string_view body, uint64_t t0) {
    if (!prog.native) {
      forward_ring(c, 1, body, t0);
      return;
    }
    JDoc doc;
    if (!json_parse(body.data(), body.size(), doc)) {
      respond_error(c, 400, "MICROSERVICE_BAD_DATA", "Invalid JSON body");
      metrics.observe_api("feedback", 400, 1e-9 * (now_ns() - t0));
      return;
    }
    double reward = 0;
    std::vector<std::pair<std::string_view, int>> routing_entries;
    if (doc.nodes[0].type == JValue::Obj) {
      if (auto* r = doc.get(doc.nodes[0], "reward")) reward = jnum(*r);
      if (auto* resp = doc.get(doc.nodes[0], "response"))
        if (resp->type == JValue::Obj)
          if (auto* meta = doc.get(*resp, "meta"))
            if (meta->type == JValue::Obj)
              if (auto* routing = doc.get(*meta, "routing"))
                if (routing->type == JValue::Obj)
                  for (int i = 0; i < routing->n_children; ++i) {
                    const auto& m = doc.obj_members[routing->first_child + i];
                    const JValue& v = doc.nodes[m.second];
                    int branch;
                    if (!routing_value_to_int(v, branch)) {
                      // Meta.from_dict int(v) raises on these -> engine 400s
                      respond_error(c, 400, "MICROSERVICE_BAD_DATA",
                                    "routing values must be integers");
                      metrics.observe_api("feedback", 400, 1e-9 * (now_ns() - t0));
                      return;
                    }
                    routing_entries.push_back({m.first, branch});
                  }
    }
    if (!feedback_walk(prog.root, routing_entries, reward)) {
      respond_error(c, 400, "BAD_ROUTING",
                    "Feedback routing names a branch outside the unit's children");
      metrics.observe_api("feedback", 400, 1e-9 * (now_ns() - t0));
      return;
    }
    ++metrics.feedback_events;
    if (reward != 0) metrics.feedback_reward += reward < 0 ? -reward : reward;
    respond(c, 200, "OK", "{\"meta\": {}}");
    metrics.observe_api("feedback", 200, 1e-9 * (now_ns() - t0));
  }

  // ---- ring fallback ----
  void forward_ring(Conn& c, uint8_t kind, std::string_view body, uint64_t t0) {
    const char* method = kind == 1 ? "feedback" : "predictions";
    if (!req_ring || !resp_ring) {
      respond_error(c, 500, "INTERNAL_ERROR", "no native program and no engine ring");
      metrics.observe_api(method, 500, 1e-9 * (now_ns() - t0));
      return;
    }
    uint32_t req_id = next_req_id++;
    // frame: u16 worker | u32 req_id | u8 kind | body  (transport/ipc.py)
    std::vector<char> frame(7 + body.size());
    memcpy(frame.data(), &ring_worker_id, 2);
    memcpy(frame.data() + 2, &req_id, 4);
    frame[6] = (char)kind;
    memcpy(frame.data() + 7, body.data(), body.size());
    int rc = scr_push(req_ring, frame.data(), (uint32_t)frame.size());
    if (rc != 0) {
      respond_error(c, rc == -2 ? 413 : 503,
                    rc == -2 ? "PAYLOAD_TOO_LARGE" : "ENGINE_BUSY",
                    rc == -2 ? "request larger than ring slot" : "engine request ring full");
      metrics.observe_api(method, rc == -2 ? 413 : 503, 1e-9 * (now_ns() - t0));
      return;
    }
    c.waiting_ring = true;
    pending[req_id] = {c.fd, c.gen, t0, kind == 1};
    arm_timer();
  }


  // Push one device site's kind-2 frame. Returns 0 ok, ring error codes
  // otherwise. Frame: u16 worker | u32 rid | u8 2 | u16 model | u8 method
  // | u8 ndim | u32 dims[] | f64 data.
  int push_site_frame(DevExec* st, size_t s) {
    DevSite& site = st->sites[s];
    site.req_id = next_req_id++;
    const Unit& u = prog.units[site.unit_idx];
    size_t ndim = site.req_dims.size();
    size_t n_extra = site.chain.size();
    // 7 ring hdr + 2 mid + 1 method + 1 n_extra + 3/stage + 1 ndim + dims + data
    std::vector<char> frame(12 + 3 * n_extra + 4 * ndim +
                            8 * site.req_vals.size());
    memcpy(frame.data(), &ring_worker_id, 2);
    memcpy(frame.data() + 2, &site.req_id, 4);
    frame[6] = 2;  // KIND_MODEL
    uint16_t mid = (uint16_t)u.model_id;
    memcpy(frame.data() + 7, &mid, 2);
    frame[9] = (char)site.method;
    size_t off = 10;
    frame[off++] = (char)(uint8_t)n_extra;
    for (int m : site.chain) {  // fused downstream stages, one RTT total
      const Unit& cu = prog.units[st->sites[m].unit_idx];
      uint16_t cmid = (uint16_t)cu.model_id;
      memcpy(frame.data() + off, &cmid, 2);
      frame[off + 2] = (char)st->sites[m].method;
      off += 3;
    }
    frame[off++] = (char)(uint8_t)ndim;
    memcpy(frame.data() + off, site.req_dims.data(), 4 * ndim);
    off += 4 * ndim;
    memcpy(frame.data() + off, site.req_vals.data(), 8 * site.req_vals.size());
    int rc = scr_push(req_ring, frame.data(), (uint32_t)frame.size());
    if (rc != 0) return rc;
    site.issued = true;
    site.owns_pending = true;
    pending_dev[site.req_id] = {st, (int)s};
    for (int m : site.chain) st->sites[m].issued = true;
    site.req_vals.clear();
    site.req_vals.shrink_to_fit();
    return 0;
  }

  // Collapse linear dependency runs into fused chains: a site whose output
  // feeds exactly ONE downstream site carries that site (and its sole
  // successors) inside its own frame — the transform->model path costs one
  // ring round-trip instead of one per hop.
  static void plan_chains(DevExec* st) {
    size_t n = st->sites.size();
    std::vector<int> dep_count(n, 0), sole_dep(n, -1);
    for (size_t i = 0; i < n; ++i) {
      int in = st->sites[i].input_site;
      if (in >= 0) {
        if (++dep_count[in] == 1) sole_dep[in] = (int)i;
        else sole_dep[in] = -1;
      }
    }
    for (size_t i = 0; i < n; ++i) {
      DevSite& s = st->sites[i];
      if (s.input_site >= 0 && dep_count[s.input_site] == 1)
        s.chain_member = true;
    }
    for (size_t i = 0; i < n; ++i) {
      DevSite& s = st->sites[i];
      if (s.chain_member) continue;  // heads only
      int cur = (int)i;
      while (sole_dep[cur] >= 0) {
        s.chain.push_back(sole_dep[cur]);
        cur = sole_dep[cur];
      }
      // the wire carries chain length as u8: a run deeper than 255 extras
      // does not fuse at all — members revert to the (correct, per-hop)
      // deferred path rather than a truncated frame
      if (s.chain.size() > 255) {
        for (int m : s.chain) st->sites[m].chain_member = false;
        s.chain.clear();
      }
    }
  }

  // ---- device graphs: parse numeric payload, eval, ship model calls ----
  void handle_predictions_device(Conn& c, std::string_view body, uint64_t t0) {
    auto* st = new DevExec();
    st->body.assign(body.data(), body.size());
    JDoc& doc = st->doc;
    if (!json_parse(st->body.data(), st->body.size(), doc)) {
      std::string info =
          std::string("Invalid JSON body: ") + (doc.err ? doc.err : "parse error");
      respond_error(c, 400, "MICROSERVICE_BAD_DATA", info);
      metrics.observe_api("predictions", 400, 1e-9 * (now_ns() - t0));
      delete st;
      return;
    }
    const JValue& root = doc.nodes[0];
    if (root.type != JValue::Obj) {
      respond_error(c, 400, "MICROSERVICE_BAD_DATA", "request must be a JSON object");
      metrics.observe_api("predictions", 400, 1e-9 * (now_ns() - t0));
      delete st;
      return;
    }
    const JValue* data = doc.get(root, "data");
    const JValue* tensor = nullptr;
    PKind pkind = PKind::None;
    if (data && data->type == JValue::Obj) {
      if (doc.get(*data, "ndarray")) pkind = PKind::NDArray;
      else if ((tensor = doc.get(*data, "tensor"))) pkind = PKind::Tensor;
    } else if (doc.get(root, "strData") || doc.get(root, "binData") ||
               doc.get(root, "jsonData")) {
      pkind = PKind::Str;  // any non-numeric payload: full-graph ring below
    }
    // Exotic payloads (echo semantics, jsonData, request names feeding a
    // component, ragged/deep arrays, odd tensors) ride the full-graph ring:
    // the Python engine is the semantics oracle off the numeric hot path.
    if (pkind != PKind::NDArray && pkind != PKind::Tensor) {
      delete st;
      return forward_ring(c, 0, body, t0);
    }
    if (data && doc.get(*data, "names")) {
      delete st;
      return forward_ring(c, 0, body, t0);
    }

    DVal input;
    input.dtype = 1;  // request JSON numbers are python floats
    if (pkind == PKind::NDArray) {
      const JValue* nd = doc.get(*data, "ndarray");
      if (nd->type != JValue::Arr) {
        respond_error(c, 400, "MICROSERVICE_BAD_DATA", "ndarray must be an array");
        metrics.observe_api("predictions", 400, 1e-9 * (now_ns() - t0));
        delete st;
        return;
      }
      bool two_d = nd->n_children > 0 && doc.item(*nd, 0)->type == JValue::Arr;
      if (!two_d) {
        input.dims = {(uint32_t)nd->n_children};
        for (int i = 0; i < nd->n_children; ++i) {
          const JValue* e = doc.item(*nd, i);
          if (e->type != JValue::Num) { delete st; return forward_ring(c, 0, body, t0); }
          input.vals.push_back(jnum(*e));
        }
      } else {
        int rows = nd->n_children;
        int cols = doc.item(*nd, 0)->n_children;
        input.dims = {(uint32_t)rows, (uint32_t)cols};
        input.vals.reserve((size_t)rows * cols);
        for (int r = 0; r < rows; ++r) {
          const JValue* row = doc.item(*nd, r);
          if (row->type != JValue::Arr || row->n_children != cols)
            { delete st; return forward_ring(c, 0, body, t0); }
          for (int i = 0; i < cols; ++i) {
            const JValue* e = doc.item(*row, i);
            if (e->type != JValue::Num) { delete st; return forward_ring(c, 0, body, t0); }
            input.vals.push_back(jnum(*e));
          }
        }
      }
    } else {
      const JValue* shape = doc.get(*tensor, "shape");
      const JValue* values = doc.get(*tensor, "values");
      if (!shape || shape->type != JValue::Arr || shape->n_children < 1 ||
          shape->n_children > 8 || !values)
        { delete st; return forward_ring(c, 0, body, t0); }
      uint64_t prod = 1;
      for (int i = 0; i < shape->n_children; ++i) {
        double d = jnum(*doc.item(*shape, i));
        if (d < 1 || d != (double)(uint32_t)d) { delete st; return forward_ring(c, 0, body, t0); }
        input.dims.push_back((uint32_t)d);
        prod *= (uint64_t)d;
      }
      if (prod != (uint64_t)values->n_children) { delete st; return forward_ring(c, 0, body, t0); }
      input.vals.reserve(values->n_children);
      for (int i = 0; i < values->n_children; ++i) {
        const JValue* e = doc.item(*values, i);
        if (e->type != JValue::Num) { delete st; return forward_ring(c, 0, body, t0); }
        input.vals.push_back(jnum(*e));
      }
    }

    Kind owner = Kind::SimpleModel;
    int owner_site = -1;
    DVal result;
    if (!eval_device(prog, prog.root, rng, input, st->ex, st->sites,
                     st->metric_srcs, result, owner, owner_site)) {
      respond_error(c, st->ex.err_code, st->ex.err_reason, st->ex.err_info);
      metrics.observe_api("predictions", st->ex.err_code, 1e-9 * (now_ns() - t0));
      delete st;
      return;
    }
    st->result = std::move(result);
    st->owner = owner;
    st->owner_site = owner_site;
    st->resp_kind = pkind;

    if (st->sites.empty()) {
      // the route never reached a device model: finish synchronously
      std::vector<double> vals;
      std::vector<uint32_t> dims;
      uint8_t dt;
      std::string err;
      if (!resolve_dval(st->result, st->sites, vals, dims, dt, err)) {
        respond_error(c, 500, "INTERNAL_ERROR", err);
        metrics.observe_api("predictions", 500, 1e-9 * (now_ns() - t0));
      } else {
        build_device_response(c, doc, *st, vals, dims);
        metrics.observe_api("predictions", 200, 1e-9 * (now_ns() - t0));
      }
      delete st;
      return;
    }

    if (!req_ring || !resp_ring) {
      respond_error(c, 500, "INTERNAL_ERROR", "device models need the engine ring");
      metrics.observe_api("predictions", 500, 1e-9 * (now_ns() - t0));
      delete st;
      return;
    }
    plan_chains(st);
    for (size_t s = 0; s < st->sites.size(); ++s) {
      if (st->sites[s].input_site >= 0) continue;  // deferred: pushed on dep completion
      int rc = push_site_frame(st, s);
      if (rc != 0) {
        drop_dev_exec(st);
        respond_error(c, rc == -2 ? 413 : 503,
                      rc == -2 ? "PAYLOAD_TOO_LARGE" : "ENGINE_BUSY",
                      rc == -2 ? "tensor larger than ring slot"
                               : "engine request ring full");
        metrics.observe_api("predictions", rc == -2 ? 413 : 503,
                            1e-9 * (now_ns() - t0));
        return;
      }
    }
    st->conn_fd = c.fd;
    st->conn_gen = c.gen;
    st->t0 = t0;
    st->outstanding = (int)st->sites.size();
    c.waiting_ring = true;
    arm_timer();
  }

  void drop_dev_exec(DevExec* st) {
    // only issued sites own pending entries: a never-issued deferred site
    // still has req_id 0, which after u32 wraparound could name a live
    // request's entry
    for (auto& site : st->sites)
      if (site.owns_pending) pending_dev.erase(site.req_id);
    delete st;
  }

  // Build + send the 200 response for a device-graph request. `doc` is the
  // parsed request (either the live request or a re-parse of st->body).
  void build_device_response(Conn& c, JDoc& doc, DevExec& st,
                             const std::vector<double>& vals,
                             const std::vector<uint32_t>& dims) {
    ExecOut& ex = st.ex;
    const JValue& root = doc.nodes[0];
    const JValue* meta = doc.get(root, "meta");
    std::string_view req_puid;
    const JValue* req_tags = nullptr;
    const JValue* req_routing = nullptr;
    const JValue* req_path = nullptr;
    const JValue* req_metrics = nullptr;
    if (meta && meta->type == JValue::Obj) {
      if (auto* v = doc.get(*meta, "puid")) req_puid = v->sv;
      if (auto* v = doc.get(*meta, "tags")) req_tags = v;
      if (auto* v = doc.get(*meta, "routing")) req_routing = v;
      if (auto* v = doc.get(*meta, "requestPath")) req_path = v;
      if (auto* v = doc.get(*meta, "metrics")) req_metrics = v;
    }
    // executor fragments: parse names/tags/metrics spans per done site
    std::vector<JDoc> frag_docs(st.sites.size());
    std::vector<const JValue*> frag_names(st.sites.size(), nullptr);
    std::vector<const JValue*> frag_tags(st.sites.size(), nullptr);
    std::vector<const JValue*> frag_metrics(st.sites.size(), nullptr);
    for (size_t i = 0; i < st.sites.size(); ++i) {
      const std::string& frag = st.sites[i].fragment;
      if (frag.empty()) continue;
      if (!json_parse(frag.data(), frag.size(), frag_docs[i])) continue;
      const JValue& froot = frag_docs[i].nodes[0];
      if (froot.type != JValue::Obj) continue;
      frag_names[i] = frag_docs[i].get(froot, "names");
      frag_tags[i] = frag_docs[i].get(froot, "tags");
      frag_metrics[i] = frag_docs[i].get(froot, "metrics");
    }

    char puid[33];
    if (req_puid.empty()) rng.puid_hex(puid);
    Buf body_buf;
    body_buf.append("{\"meta\": {\"puid\": \"");
    if (req_puid.empty()) body_buf.append(puid, 32);
    else body_buf.append(req_puid);
    body_buf.push('"');

    // ---- tags: device fragments + bandit fragment + request echo.
    // Precedence mirrors the stub path's fuzz-verified rules: the request's
    // value wins on a key collision; among device sites, first wins.
    bool have_bandit = !ex.bandit_tags.empty();
    bool have_dev_tags = false;
    for (auto* t : frag_tags)
      if (t && t->n_children > 0) have_dev_tags = true;
    if (req_tags && req_tags->type != JValue::Obj) {
      if (req_tags->n_children > 0) {
        body_buf.append(", \"tags\": ");
        body_buf.append(req_tags->raw);
      }
    } else if (have_bandit || have_dev_tags ||
               (req_tags && req_tags->n_children > 0)) {
      body_buf.append(", \"tags\": {");
      bool first = true;
      auto req_tag_value = [&](std::string_view key) -> const JValue* {
        if (!req_tags) return nullptr;
        for (int i = 0; i < req_tags->n_children; ++i) {
          const auto& m = doc.obj_members[req_tags->first_child + i];
          if (m.first == key) return &doc.nodes[m.second];
        }
        return nullptr;
      };
      std::vector<std::string_view> emitted;
      auto already = [&](std::string_view key) {
        for (auto& k : emitted)
          if (k == key) return true;
        return false;
      };
      if (have_bandit) {
        const Unit& bu = prog.units[ex.bandit_tags[0].first];
        body_buf.append("\"bandit\": ");
        if (auto* v = req_tag_value("bandit")) body_buf.append(v->raw);
        else {
          body_buf.push('"');
          body_buf.append(kind_class(bu.kind));
          body_buf.push('"');
        }
        body_buf.append(", \"branch_means\": ");
        if (auto* v = req_tag_value("branch_means")) body_buf.append(v->raw);
        else {
          body_buf.push('[');
          const auto& means = ex.bandit_tags[0].second;
          for (size_t i = 0; i < means.size(); ++i) {
            if (i) body_buf.append(", ");
            body_buf.append_double(nearbyint(means[i] * 1e6) / 1e6);
          }
          body_buf.push(']');
        }
        emitted.push_back("bandit");
        emitted.push_back("branch_means");
        first = false;
      }
      for (size_t s = 0; s < st.sites.size(); ++s) {
        if (!frag_tags[s]) continue;
        for (int i = 0; i < frag_tags[s]->n_children; ++i) {
          const auto& m = frag_docs[s].obj_members[frag_tags[s]->first_child + i];
          if (already(m.first)) continue;
          if (!first) body_buf.append(", ");
          first = false;
          body_buf.push('"');
          body_buf.append(m.first);
          body_buf.append("\": ");
          if (auto* v = req_tag_value(m.first)) body_buf.append(v->raw);
          else body_buf.append(frag_docs[s].nodes[m.second].raw);
          emitted.push_back(m.first);
        }
      }
      if (req_tags) {
        for (int i = 0; i < req_tags->n_children; ++i) {
          const auto& m = doc.obj_members[req_tags->first_child + i];
          if (already(m.first)) continue;
          if (!first) body_buf.append(", ");
          first = false;
          body_buf.push('"');
          body_buf.append(m.first);
          body_buf.append("\": ");
          body_buf.append(doc.nodes[m.second].raw);
        }
      }
      body_buf.push('}');
    }

    // ---- routing (same as stub path) ----
    if (!ex.routing.empty() || (req_routing && req_routing->n_children > 0)) {
      body_buf.append(", \"routing\": {");
      bool first = true;
      for (auto& [name, branch] : ex.routing) {
        if (!first) body_buf.append(", ");
        first = false;
        body_buf.push('"');
        body_buf.append(name);
        body_buf.append("\": ");
        body_buf.append_i64(branch);
      }
      if (req_routing) {
        for (int i = 0; i < req_routing->n_children; ++i) {
          const auto& m = doc.obj_members[req_routing->first_child + i];
          bool dup = false;
          for (auto& [name, _] : ex.routing)
            if (name == m.first) dup = true;
          if (dup) continue;
          if (!first) body_buf.append(", ");
          first = false;
          body_buf.push('"');
          body_buf.append(m.first);
          body_buf.append("\": ");
          body_buf.append(doc.nodes[m.second].raw);
        }
      }
      body_buf.push('}');
    }

    // ---- requestPath ----
    body_buf.append(", \"requestPath\": {");
    {
      bool first = true;
      if (req_path) {
        for (int i = 0; i < req_path->n_children; ++i) {
          const auto& m = doc.obj_members[req_path->first_child + i];
          bool dup = false;
          for (auto& [name, _] : ex.path)
            if (name == m.first) dup = true;
          if (dup) continue;
          if (!first) body_buf.append(", ");
          first = false;
          body_buf.push('"');
          body_buf.append(m.first);
          body_buf.append("\": ");
          body_buf.append(doc.nodes[m.second].raw);
        }
      }
      for (auto& [name, cls] : ex.path) {
        if (!first) body_buf.append(", ");
        first = false;
        body_buf.push('"');
        body_buf.append(name);
        body_buf.append("\": \"");
        body_buf.append(cls);
        body_buf.push('"');
      }
    }
    body_buf.push('}');

    // ---- metrics: owner's source first, then request-carried, then the
    // remaining executed sources in traversal order (engine merge order) ----
    {
      static const char* kModelMetrics =
          "{\"key\": \"mycounter\", \"type\": \"COUNTER\", \"value\": 1.0}, "
          "{\"key\": \"mygauge\", \"type\": \"GAUGE\", \"value\": 100.0}, "
          "{\"key\": \"mytimer\", \"type\": \"TIMER\", \"value\": 20.6}";
      bool any_dev_metrics = false;
      for (auto* m : frag_metrics)
        if (m && m->n_children > 0) any_dev_metrics = true;
      bool have_any = ex.model_visits > 0 || any_dev_metrics ||
                      (req_metrics && req_metrics->n_children > 0);
      if (have_any) {
        body_buf.append(", \"metrics\": [");
        bool first = true;
        auto emit_site = [&](int site) {
          if (!frag_metrics[site] || frag_metrics[site]->n_children == 0) return;
          for (int i = 0; i < frag_metrics[site]->n_children; ++i) {
            if (!first) body_buf.append(", ");
            first = false;
            body_buf.append(
                frag_docs[site].item(*frag_metrics[site], i)->raw);
          }
        };
        auto emit_builtin = [&]() {
          if (!first) body_buf.append(", ");
          first = false;
          body_buf.append(kModelMetrics);
        };
        auto emit_request_metrics = [&]() {
          if (!req_metrics) return;
          for (int i = 0; i < req_metrics->n_children; ++i) {
            if (!first) body_buf.append(", ");
            first = false;
            body_buf.append(doc.item(*req_metrics, i)->raw);
          }
        };
        // Engine merge order (probed against GraphEngine, fused default):
        // non-combiner owner -> component metrics in REVERSE traversal
        // order (flow-final node first, upstream transforms after), request
        // metrics LAST. Combiner owner -> request metrics FIRST, children
        // in traversal order (the fused aggregate's order).
        if (st.owner == Kind::AverageCombiner) {
          emit_request_metrics();
          for (auto& src : st.metric_srcs) {
            if (src.site == -1) emit_builtin();
            else emit_site(src.site);
          }
        } else {
          for (auto it2 = st.metric_srcs.rbegin(); it2 != st.metric_srcs.rend();
               ++it2) {
            if (it2->site == -1) emit_builtin();
            else emit_site(it2->site);
          }
          emit_request_metrics();
        }
        body_buf.push(']');
      }
    }
    body_buf.push('}');

    // ---- data payload: real values ----
    body_buf.append(", \"data\": {");
    bool wrote_names = false;
    if (st.owner == Kind::DeviceModel && st.owner_site >= 0) {
      if (frag_names[st.owner_site]) {
        body_buf.append("\"names\": ");
        body_buf.append(frag_names[st.owner_site]->raw);
        wrote_names = true;
      }
    } else if (st.owner == Kind::AverageCombiner) {
      if (dims.size() > 1) {
        body_buf.append("\"names\": [");
        for (uint32_t i = 0; i < dims[1]; ++i) {
          if (i) body_buf.append(", ");
          body_buf.append("\"t:");
          body_buf.append_i64(i);
          body_buf.push('"');
        }
        body_buf.push(']');
        wrote_names = true;
      }
    } else {
      body_buf.append("\"names\": [\"class0\", \"class1\", \"class2\"]");
      wrote_names = true;
    }
    if (wrote_names) body_buf.append(", ");
    if (st.resp_kind == PKind::NDArray) {
      // nested arrays by dims (row-major)
      body_buf.append("\"ndarray\": ");
      size_t pos = 0;
      std::function<void(size_t)> emit_nd = [&](size_t d) {
        if (d == dims.size()) {
          body_buf.append_double(vals[pos++]);
          return;
        }
        body_buf.push('[');
        for (uint32_t i = 0; i < dims[d]; ++i) {
          if (i) body_buf.append(", ");
          emit_nd(d + 1);
        }
        body_buf.push(']');
      };
      // 0-d result (scalar predict): emit_nd(0) writes the bare number,
      // matching the engine's tolist() of a 0-d array
      if (vals.empty()) body_buf.append("[]");
      else emit_nd(0);
      body_buf.push('}');
    } else {
      body_buf.append("\"tensor\": {\"shape\": [");
      for (size_t i = 0; i < dims.size(); ++i) {
        if (i) body_buf.append(", ");
        body_buf.append_i64((int64_t)dims[i]);
      }
      body_buf.append("], \"values\": [");
      for (size_t i = 0; i < vals.size(); ++i) {
        if (i) body_buf.append(", ");
        body_buf.append_double(vals[i]);
      }
      body_buf.append("]}}");
    }
    body_buf.push('}');

    http_head(c.outbuf, 200, "OK", body_buf.size(),
              "application/json; charset=utf-8", c.want_close);
    c.outbuf.append(body_buf.data(), body_buf.size());
    metrics.mycounter += ex.model_visits;
    if (ex.model_visits) {
      metrics.mygauge = 100.0;
      for (int i = 0; i < ex.model_visits; ++i)
        metrics.mytimer.observe(20.6 / 1000.0);
      metrics.custom_seen += ex.model_visits;
    }
  }

  // All sites landed: resolve the dataflow over st->doc/body and respond
  // (JSON for REST parks, proto for gRPC parks).
  void finish_device(DevExec* st) {
    Conn& c = conn(st->conn_fd);
    bool conn_ok = c.fd == st->conn_fd && c.gen == st->conn_gen;
    if (!conn_ok) {
      delete st;
      return;
    }
    std::vector<double> vals;
    std::vector<uint32_t> dims;
    uint8_t dt;
    std::string err;
    bool resolved = resolve_dval(st->result, st->sites, vals, dims, dt, err);
    if (st->is_grpc) {
      if (!resolved) {
        grpc_trailers_error(c, st->h2_sid, 13, err);
        metrics.observe_api("predictions", 500, 1e-9 * (now_ns() - st->t0));
      } else {
        send_grpc_device_response(c, *st, vals, dims);
        metrics.observe_api("predictions", 200, 1e-9 * (now_ns() - st->t0));
      }
      flush_out(c);
      delete st;
      return;
    }
    c.waiting_ring = false;
    if (!resolved) {
      respond_error(c, 500, "INTERNAL_ERROR", err);
      metrics.observe_api("predictions", 500, 1e-9 * (now_ns() - st->t0));
    } else {
      build_device_response(c, st->doc, *st, vals, dims);
      metrics.observe_api("predictions", 200, 1e-9 * (now_ns() - st->t0));
    }
    flush_out(c);
    if (c.fd >= 0 && c.in.size() > 0) process_in(c);
    delete st;
  }

  static int grpc_code_from_http(int http) {
    if (http == 400) return 3;   // INVALID_ARGUMENT
    if (http == 503) return 14;  // UNAVAILABLE
    if (http == 504) return 4;   // DEADLINE_EXCEEDED
    return 13;                   // INTERNAL
  }

  // Park a gRPC stream on a full-proto ring round-trip (kind 3 predict /
  // kind 4 feedback). The engine answers with proto bytes (status 0) or
  // 1-byte-grpc-code + message (status 1).
  void forward_ring_grpc(Conn& c, uint32_t sid, uint8_t kind,
                         std::string_view body, uint64_t t0) {
    const char* method = kind == 4 ? "feedback" : "predictions";
    if (!req_ring || !resp_ring) {
      grpc_trailers_error(c, sid, 12, "no native program and no engine ring");
      metrics.observe_api(method, 501, 1e-9 * (now_ns() - t0));
      return;
    }
    uint32_t req_id = next_req_id++;
    std::vector<char> frame(7 + body.size());
    memcpy(frame.data(), &ring_worker_id, 2);
    memcpy(frame.data() + 2, &req_id, 4);
    frame[6] = (char)kind;
    memcpy(frame.data() + 7, body.data(), body.size());
    int rc = scr_push(req_ring, frame.data(), (uint32_t)frame.size());
    if (rc != 0) {
      grpc_trailers_error(c, sid, rc == -2 ? 3 : 14,
                          rc == -2 ? "request larger than ring slot"
                                   : "engine request ring full");
      metrics.observe_api(method, rc == -2 ? 413 : 503, 1e-9 * (now_ns() - t0));
      return;
    }
    pending_grpc[req_id] = {c.fd, c.gen, sid, t0, kind == 4};
    arm_timer();
  }

  // Native device execution for a gRPC tensor request: same dataflow as the
  // REST device path, but the park completes with a proto response. The
  // proto is parsed ONCE over the DevExec's body copy (spans survive the
  // park — the parse-once discipline of the REST path's JDoc).
  void handle_grpc_device(Conn& c, uint32_t sid, std::string_view body,
                          uint64_t t0) {
    auto* st = new DevExec();
    st->is_grpc = true;
    st->h2_sid = sid;
    st->body.assign(body.data(), body.size());
    st->preq.want_values = true;
    if (!pb_parse_seldon_message({st->body.data(), st->body.size()}, st->preq)) {
      grpc_trailers_error(c, sid, 3, "cannot parse SeldonMessage");
      metrics.observe_api("predictions", 400, 1e-9 * (now_ns() - t0));
      delete st;
      return;
    }
    if (st->preq.in.kind != PKind::Tensor || st->preq.has_names ||
        st->preq.dims.empty() || st->preq.dims.size() > 8) {
      delete st;
      forward_ring_grpc(c, sid, 3, body, t0);
      return;
    }
    if (st->preq.tensor_prod != st->preq.tensor_nvals) {
      grpc_trailers_error(c, sid, 3, "tensor values do not fit shape");
      metrics.observe_api("predictions", 400, 1e-9 * (now_ns() - t0));
      delete st;
      return;
    }
    DVal input;
    input.dtype = 1;
    input.dims = std::move(st->preq.dims);
    input.vals = std::move(st->preq.vals);

    Kind owner = Kind::SimpleModel;
    int owner_site = -1;
    DVal result;
    if (!eval_device(prog, prog.root, rng, input, st->ex, st->sites,
                     st->metric_srcs, result, owner, owner_site)) {
      grpc_trailers_error(c, sid, st->ex.err_code == 400 ? 3 : 13,
                          st->ex.err_info);
      metrics.observe_api("predictions", st->ex.err_code,
                          1e-9 * (now_ns() - t0));
      delete st;
      return;
    }
    st->result = std::move(result);
    st->owner = owner;
    st->owner_site = owner_site;
    st->resp_kind = PKind::Tensor;
    st->conn_fd = c.fd;
    st->conn_gen = c.gen;
    st->t0 = t0;

    if (st->sites.empty()) {
      std::vector<double> vals;
      std::vector<uint32_t> dims;
      uint8_t dt;
      std::string err;
      if (!resolve_dval(st->result, st->sites, vals, dims, dt, err)) {
        grpc_trailers_error(c, sid, 13, err);
        metrics.observe_api("predictions", 500, 1e-9 * (now_ns() - t0));
      } else {
        send_grpc_device_response(c, *st, vals, dims);
        metrics.observe_api("predictions", 200, 1e-9 * (now_ns() - t0));
      }
      delete st;
      return;
    }
    if (!req_ring || !resp_ring) {
      grpc_trailers_error(c, sid, 13, "device models need the engine ring");
      metrics.observe_api("predictions", 500, 1e-9 * (now_ns() - t0));
      delete st;
      return;
    }
    plan_chains(st);
    for (size_t s = 0; s < st->sites.size(); ++s) {
      if (st->sites[s].input_site >= 0) continue;  // deferred
      int rc = push_site_frame(st, s);
      if (rc != 0) {
        drop_dev_exec(st);
        grpc_trailers_error(c, sid, rc == -2 ? 3 : 14,
                            rc == -2 ? "tensor larger than ring slot"
                                     : "engine request ring full");
        metrics.observe_api("predictions", rc == -2 ? 413 : 503,
                            1e-9 * (now_ns() - t0));
        return;
      }
    }
    st->outstanding = (int)st->sites.size();
    arm_timer();
  }

  // JSON value -> google.protobuf.Value wire bytes (tags fragments from the
  // executor: numbers, strings, bools, lists, objects).
  static void json_to_pb_value(const JDoc& doc, const JValue& v, Buf& out) {
    PbWriter w{out};
    switch (v.type) {
      case JValue::Num:
        w.tag(2, 1);
        w.fixed64_raw(jnum(v));
        break;
      case JValue::Str:
        w.str(3, v.sv);
        break;
      case JValue::Bool:
        w.tag(4, 0);
        w.varint(v.b ? 1 : 0);
        break;
      case JValue::Arr: {
        Buf lv;
        for (int i = 0; i < v.n_children; ++i) {
          Buf item;
          json_to_pb_value(doc, *doc.item(v, i), item);
          PbWriter lw{lv};
          lw.tag(1, 2);
          lw.varint(item.size());
          lv.append(item.data(), item.size());
        }
        w.tag(6, 2);
        w.varint(lv.size());
        out.append(lv.data(), lv.size());
        break;
      }
      case JValue::Obj: {
        Buf st;
        for (int i = 0; i < v.n_children; ++i) {
          const auto& m = doc.obj_members[v.first_child + i];
          Buf item;
          json_to_pb_value(doc, doc.nodes[m.second], item);
          Buf e;
          PbWriter ew{e};
          ew.str(1, m.first);
          ew.tag(2, 2);
          ew.varint(item.size());
          e.append(item.data(), item.size());
          PbWriter sw{st};
          sw.tag(1, 2);
          sw.varint(e.size());
          st.append(e.data(), e.size());
        }
        w.tag(5, 2);
        w.varint(st.size());
        out.append(st.data(), st.size());
        break;
      }
      case JValue::Null:
        w.tag(1, 0);
        w.varint(0);
        break;
    }
  }

  // Proto response for a completed device-graph gRPC request: the proto
  // twin of build_device_response (meta echo/routing/path/metrics, real
  // tensor values, names from the owner site's executor fragment).
  void send_grpc_device_response(Conn& c, DevExec& st,
                                 const std::vector<double>& vals,
                                 const std::vector<uint32_t>& dims) {
    // meta echo spans parsed once at admission, pointing into st.body
    PbSeldonMsg& req = st.preq;
    ExecOut& ex = st.ex;
    // executor fragments: names (owner) + metrics/tags per site
    std::vector<JDoc> frag_docs(st.sites.size());
    std::vector<const JValue*> frag_names(st.sites.size(), nullptr);
    std::vector<const JValue*> frag_metrics(st.sites.size(), nullptr);
    std::vector<const JValue*> frag_tags(st.sites.size(), nullptr);
    for (size_t i = 0; i < st.sites.size(); ++i) {
      const std::string& frag = st.sites[i].fragment;
      if (frag.empty()) continue;
      if (!json_parse(frag.data(), frag.size(), frag_docs[i])) continue;
      const JValue& froot = frag_docs[i].nodes[0];
      if (froot.type != JValue::Obj) continue;
      frag_names[i] = frag_docs[i].get(froot, "names");
      frag_metrics[i] = frag_docs[i].get(froot, "metrics");
      frag_tags[i] = frag_docs[i].get(froot, "tags");
    }

    Buf meta;
    PbWriter mw{meta};
    if (!req.puid.empty()) {
      mw.str(1, req.puid);
    } else {
      char puid[33];
      rng.puid_hex(puid);
      mw.str(1, {puid, 32});
    }
    if (!ex.bandit_tags.empty()) {
      const Unit& bu = prog.units[ex.bandit_tags[0].first];
      {
        Buf val;
        PbWriter vw{val};
        vw.str(3, kind_class(bu.kind));
        Buf e;
        PbWriter ew{e};
        ew.str(1, "bandit");
        ew.tag(2, 2);
        ew.varint(val.size());
        e.append(val.data(), val.size());
        mw.tag(2, 2);
        mw.varint(e.size());
        meta.append(e.data(), e.size());
      }
      {
        Buf lv;
        for (double m : ex.bandit_tags[0].second) {
          Buf num;
          PbWriter nw{num};
          nw.tag(2, 1);
          nw.fixed64_raw(nearbyint(m * 1e6) / 1e6);
          PbWriter lw{lv};
          lw.tag(1, 2);
          lw.varint(num.size());
          lv.append(num.data(), num.size());
        }
        Buf val;
        PbWriter vw{val};
        vw.tag(6, 2);
        vw.varint(lv.size());
        val.append(lv.data(), lv.size());
        Buf e;
        PbWriter ew{e};
        ew.str(1, "branch_means");
        ew.tag(2, 2);
        ew.varint(val.size());
        e.append(val.data(), val.size());
        mw.tag(2, 2);
        mw.varint(e.size());
        meta.append(e.data(), e.size());
      }
    }
    // device-site tags (e.g. outlier scores), before the echo so an echoed
    // request tag with the same key wins (proto map: last entry wins).
    // Among device sites the FIRST wins — same rule as the REST builder.
    std::vector<std::string_view> dev_tag_keys;
    for (size_t i = 0; i < st.sites.size(); ++i) {
      if (!frag_tags[i] || frag_tags[i]->type != JValue::Obj) continue;
      for (int k = 0; k < frag_tags[i]->n_children; ++k) {
        const auto& m = frag_docs[i].obj_members[frag_tags[i]->first_child + k];
        bool dup = false;
        for (auto kk : dev_tag_keys)
          if (kk == m.first) dup = true;
        if (dup) continue;
        dev_tag_keys.push_back(m.first);
        Buf val;
        json_to_pb_value(frag_docs[i], frag_docs[i].nodes[m.second], val);
        Buf e;
        PbWriter ew{e};
        ew.str(1, m.first);
        ew.tag(2, 2);
        ew.varint(val.size());
        e.append(val.data(), val.size());
        mw.tag(2, 2);
        mw.varint(e.size());
        meta.append(e.data(), e.size());
      }
    }
    for (auto sv : req.meta_echo) meta.append(sv);
    for (auto& [name, branch] : ex.routing) {
      Buf e;
      PbWriter ew{e};
      ew.str(1, name);
      ew.tag(2, 0);
      ew.varint((uint64_t)branch);
      mw.tag(3, 2);
      mw.varint(e.size());
      meta.append(e.data(), e.size());
    }
    for (auto& [name, cls] : ex.path) {
      Buf e;
      PbWriter ew{e};
      ew.str(1, name);
      ew.str(2, cls);
      mw.tag(4, 2);
      mw.varint(e.size());
      meta.append(e.data(), e.size());
    }
    // metrics: owner source first, request echo, remaining traversal order
    auto emit_stub_triplet = [&]() {
      struct M { const char* key; int type; float value; };
      static const M kMs[3] = {{"mycounter", 0, 1.0f}, {"mygauge", 1, 100.0f},
                               {"mytimer", 2, 20.6f}};
      for (auto& m : kMs) {
        Buf e;
        PbWriter ew{e};
        ew.str(1, m.key);
        if (m.type != 0) {
          ew.tag(2, 0);
          ew.varint((uint64_t)m.type);
        }
        ew.fixed32(3, m.value);
        mw.tag(5, 2);
        mw.varint(e.size());
        meta.append(e.data(), e.size());
      }
    };
    auto emit_site_metrics = [&](int site) {
      if (!frag_metrics[site]) return;
      for (int i = 0; i < frag_metrics[site]->n_children; ++i) {
        const JValue* m = frag_docs[site].item(*frag_metrics[site], i);
        if (!m || m->type != JValue::Obj) continue;
        Buf e;
        PbWriter ew{e};
        if (auto* k = frag_docs[site].get(*m, "key")) ew.str(1, k->sv);
        int ty = 0;
        if (auto* tv = frag_docs[site].get(*m, "type")) {
          if (tv->sv == "GAUGE") ty = 1;
          else if (tv->sv == "TIMER") ty = 2;
        }
        if (ty != 0) {
          ew.tag(2, 0);
          ew.varint((uint64_t)ty);
        }
        float fv = 0;
        if (auto* vv = frag_docs[site].get(*m, "value")) fv = (float)jnum(*vv);
        ew.fixed32(3, fv);
        mw.tag(5, 2);
        mw.varint(e.size());
        meta.append(e.data(), e.size());
      }
    };
    // same probed engine order as the REST builder: combiner owner ->
    // request first + traversal order; otherwise reverse traversal then
    // request last
    if (st.owner == Kind::AverageCombiner) {
      for (auto sv : req.req_metrics_raw) meta.append(sv);
      for (auto& src : st.metric_srcs) {
        if (src.site == -1) emit_stub_triplet();
        else emit_site_metrics(src.site);
      }
    } else {
      for (auto it2 = st.metric_srcs.rbegin(); it2 != st.metric_srcs.rend();
           ++it2) {
        if (it2->site == -1) emit_stub_triplet();
        else emit_site_metrics(it2->site);
      }
      for (auto sv : req.req_metrics_raw) meta.append(sv);
    }

    Buf msg;
    PbWriter w{msg};
    w.tag(2, 2);
    w.varint(meta.size());
    msg.append(meta.data(), meta.size());

    // DefaultData{names, tensor{shape, packed doubles}}
    Buf dd;
    PbWriter dw{dd};
    if (st.owner == Kind::DeviceModel && st.owner_site >= 0 &&
        frag_names[st.owner_site]) {
      const JValue* names = frag_names[st.owner_site];
      for (int i = 0; i < names->n_children; ++i) {
        const JValue* n = frag_docs[st.owner_site].item(*names, i);
        if (n) dw.str(1, n->sv);
      }
    } else if (st.owner == Kind::AverageCombiner) {
      if (dims.size() > 1) {
        char nb[16];
        for (uint32_t i = 0; i < dims[1]; ++i) {
          int n = snprintf(nb, sizeof(nb), "t:%u", i);
          dw.str(1, {nb, (size_t)n});
        }
      }
    } else {
      dw.str(1, "class0");
      dw.str(1, "class1");
      dw.str(1, "class2");
    }
    {
      Buf t;
      PbWriter tw{t};
      Buf shape;
      PbWriter sw{shape};
      for (uint32_t d : dims) sw.varint((uint64_t)d);
      tw.tag(1, 2);
      tw.varint(shape.size());
      t.append(shape.data(), shape.size());
      tw.tag(2, 2);
      tw.varint(vals.size() * 8);
      t.append((const char*)vals.data(), vals.size() * 8);
      dw.tag(2, 2);
      dw.varint(t.size());
      dd.append(t.data(), t.size());
    }
    w.tag(3, 2);
    w.varint(dd.size());
    msg.append(dd.data(), dd.size());
    grpc_respond_msg(c, st.h2_sid, {msg.data(), msg.size()});
    metrics.mycounter += ex.model_visits;
    if (ex.model_visits) {
      metrics.mygauge = 100.0;
      for (int i = 0; i < ex.model_visits; ++i) metrics.mytimer.observe(20.6 / 1000.0);
      metrics.custom_seen += ex.model_visits;
    }
  }

  void arm_timer() {
    if (timer_armed) return;
    itimerspec its{};
    its.it_interval.tv_nsec = 200000;  // 200us poll while work in flight
    its.it_value.tv_nsec = 200000;
    timerfd_settime(timer_fd, 0, &its, nullptr);
    timer_armed = true;
  }
  void disarm_timer() {
    if (!timer_armed) return;
    itimerspec its{};
    timerfd_settime(timer_fd, 0, &its, nullptr);
    timer_armed = false;
  }

  void run_deferred_flushes() {
    defer_flush = false;
    for (int fd : flush_queue) {
      Conn& c = conn(fd);
      if (c.fd == fd && c.flush_pending) {
        c.flush_pending = false;
        flush_out(c);
      }
    }
    flush_queue.clear();
  }

  // Re-enters false + flushes on every exit path of drain_ring_responses.
  struct FlushGuard {
    Server* s;
    explicit FlushGuard(Server* srv) : s(srv) { s->defer_flush = true; }
    ~FlushGuard() { s->run_deferred_flushes(); }
  };

  void drain_ring_responses() {
    if (!resp_ring) return;
    FlushGuard guard{this};
    if (ring_buf.size() < ring_slot) ring_buf.resize(ring_slot);
    for (;;) {
      int len = scr_pop(resp_ring, ring_buf.data(), ring_slot);
      if (len < 0) break;
      if (len < 5) continue;
      uint32_t req_id;
      memcpy(&req_id, ring_buf.data(), 4);
      uint8_t status = (uint8_t)ring_buf[4];
      auto it = pending.find(req_id);
      if (it == pending.end()) {
        auto git = pending_grpc.find(req_id);
        if (git != pending_grpc.end()) {
          GrpcPending gp = git->second;
          pending_grpc.erase(git);
          Conn& c = conn(gp.conn_fd);
          if (c.fd != gp.conn_fd || c.gen != gp.conn_gen) continue;
          const char* gmethod = gp.is_feedback ? "feedback" : "predictions";
          std::string_view body{ring_buf.data() + 5, (size_t)len - 5};
          if (status == 0) {
            grpc_respond_msg(c, gp.sid, body);
            metrics.observe_api(gmethod, 200,
                                1e-9 * (now_ns() - gp.started_ns));
          } else {
            int code = 13;
            std::string_view info = body;
            if (!body.empty()) {
              code = (uint8_t)body[0];
              info = body.substr(1);
            }
            grpc_trailers_error(c, gp.sid, code, info);
            // inverse of grpc_code_from_http for the metric label
            int http = code == 3 ? 400 : code == 14 ? 503 : code == 4 ? 504 : 500;
            metrics.observe_api(gmethod, http,
                                1e-9 * (now_ns() - gp.started_ns));
          }
          flush_out(c);
          continue;
        }
        auto dit = pending_dev.find(req_id);
        if (dit == pending_dev.end()) continue;
        DevExec* st = dit->second.first;
        int sidx = dit->second.second;
        pending_dev.erase(dit);
        if (status != 0) {
          // engine Status body: surface its code, fail the whole request
          std::string_view ebody{ring_buf.data() + 5, (size_t)len - 5};
          Conn& c = conn(st->conn_fd);
          if (c.fd == st->conn_fd && c.gen == st->conn_gen) {
            int http_code = 500;
            JDoc edoc;
            if (json_parse(ebody.data(), ebody.size(), edoc) &&
                edoc.nodes[0].type == JValue::Obj) {
              if (auto* est = edoc.get(edoc.nodes[0], "status"))
                if (auto* code = edoc.get(*est, "code")) {
                  int parsed = (int)jnum(*code);
                  if (parsed >= 400 && parsed < 600) http_code = parsed;
                }
            }
            if (st->is_grpc) {
              grpc_trailers_error(c, st->h2_sid, grpc_code_from_http(http_code),
                                  ebody);
            } else {
              c.waiting_ring = false;
              const char* text = http_code == 400 ? "Bad Request"
                                 : http_code == 503 ? "Service Unavailable"
                                                    : "Internal Server Error";
              respond(c, http_code, text, ebody);
            }
            metrics.observe_api("predictions", http_code,
                                1e-9 * (now_ns() - st->t0));
            flush_out(c);
            if (!st->is_grpc && c.fd >= 0 && c.in.size() > 0) process_in(c);
          }
          drop_dev_exec(st);
          continue;
        }
        // ok frame: u8 dtype | u8 ndim | u32 dims[] | u32 json_len | json | f64
        DevSite& site = st->sites[sidx];
        bool ok = len >= 7;
        size_t off = 0, n_elems = 1, json_len = 0;
        if (ok) {
          site.dtype = (uint8_t)ring_buf[5];
          uint8_t ndim = (uint8_t)ring_buf[6];
          off = 7 + 4ull * ndim;
          ok = ndim <= 8 && (size_t)len >= off + 4;
          if (ok) {
            site.dims.resize(ndim);
            memcpy(site.dims.data(), ring_buf.data() + 7, 4ull * ndim);
            for (uint32_t d : site.dims) n_elems *= d;
            uint32_t jl;
            memcpy(&jl, ring_buf.data() + off, 4);
            json_len = jl;
            off += 4;
            ok = (size_t)len >= off + json_len + 8 * n_elems;
          }
        }
        if (!ok) {
          Conn& c = conn(st->conn_fd);
          if (c.fd == st->conn_fd && c.gen == st->conn_gen) {
            if (st->is_grpc) {
              grpc_trailers_error(c, st->h2_sid, 13, "malformed device response");
            } else {
              c.waiting_ring = false;
              respond_error(c, 500, "INTERNAL_ERROR", "malformed device response");
            }
            metrics.observe_api("predictions", 500, 1e-9 * (now_ns() - st->t0));
            flush_out(c);
            if (!st->is_grpc && c.fd >= 0 && c.in.size() > 0) process_in(c);
          }
          drop_dev_exec(st);
          continue;
        }
        site.fragment.assign(ring_buf.data() + off, json_len);
        off += json_len;
        site.vals.resize(n_elems);
        memcpy(site.vals.data(), ring_buf.data() + off, 8 * n_elems);
        site.done = true;
        int completed = 1;
        int value_site = sidx;  // who ends up holding the returned tensor
        if (!site.chain.empty()) {
          // fused chain: fragment is a JSON array of per-stage fragments;
          // the returned tensor is the LAST stage's output
          JDoc fdoc;
          bool fok = json_parse(site.fragment.data(), site.fragment.size(), fdoc)
                     && fdoc.nodes[0].type == JValue::Arr
                     && fdoc.nodes[0].n_children == (int)site.chain.size() + 1;
          if (!fok) {
            Conn& c = conn(st->conn_fd);
            if (c.fd == st->conn_fd && c.gen == st->conn_gen) {
              if (st->is_grpc) {
                grpc_trailers_error(c, st->h2_sid, 13, "malformed chain response");
              } else {
                c.waiting_ring = false;
                respond_error(c, 500, "INTERNAL_ERROR", "malformed chain response");
              }
              metrics.observe_api("predictions", 500, 1e-9 * (now_ns() - st->t0));
              flush_out(c);
              if (!st->is_grpc && c.fd >= 0 && c.in.size() > 0) process_in(c);
            }
            drop_dev_exec(st);
            continue;
          }
          std::vector<std::string> stage_frags(site.chain.size() + 1);
          for (int fi = 0; fi <= (int)site.chain.size(); ++fi) {
            const JValue* el = fdoc.item(fdoc.nodes[0], fi);
            stage_frags[fi].assign(el->raw.data(), el->raw.size());
          }
          int last = site.chain.back();
          DevSite& last_site = st->sites[last];
          last_site.dims = site.dims;
          last_site.vals = std::move(site.vals);
          last_site.dtype = site.dtype;
          site.dims.clear();
          site.vals.clear();
          site.fragment = std::move(stage_frags[0]);
          for (size_t mi = 0; mi < site.chain.size(); ++mi) {
            DevSite& m = st->sites[site.chain[mi]];
            m.fragment = std::move(stage_frags[mi + 1]);
            m.done = true;
          }
          completed += (int)site.chain.size();
          value_site = last;
        }
        // deferred dependents: the value-holder's output is their input
        DevSite& vsite = st->sites[value_site];
        int dep_push_failed = 0;  // 0 ok, else the failing rc (-1/-2)
        for (size_t d = 0; d < st->sites.size(); ++d) {
          DevSite& dep = st->sites[d];
          if (dep.input_site != value_site || dep.issued) continue;
          dep.req_dims = vsite.dims;
          dep.req_vals = vsite.vals;
          int rc2 = push_site_frame(st, d);
          if (rc2 != 0) {
            dep_push_failed = rc2;
            break;
          }
        }
        if (dep_push_failed) {
          Conn& c = conn(st->conn_fd);
          if (c.fd == st->conn_fd && c.gen == st->conn_gen) {
            bool too_large = dep_push_failed == -2;
            if (st->is_grpc) {
              grpc_trailers_error(c, st->h2_sid, too_large ? 3 : 14,
                                  too_large ? "tensor larger than ring slot"
                                            : "engine request ring full");
            } else {
              c.waiting_ring = false;
              respond_error(c, too_large ? 413 : 503,
                            too_large ? "PAYLOAD_TOO_LARGE" : "ENGINE_BUSY",
                            too_large ? "tensor larger than ring slot"
                                      : "engine request ring full");
            }
            metrics.observe_api("predictions", too_large ? 413 : 503,
                                1e-9 * (now_ns() - st->t0));
            flush_out(c);
            if (!st->is_grpc && c.fd >= 0 && c.in.size() > 0) process_in(c);
          }
          drop_dev_exec(st);
          continue;
        }
        st->outstanding -= completed;
        if (st->outstanding == 0) finish_device(st);
        continue;
      }
      RingPending rp = it->second;
      pending.erase(it);
      Conn& c = conn(rp.conn_fd);
      if (c.fd != rp.conn_fd || c.gen != rp.conn_gen)
        continue;  // connection closed (and possibly fd reused) meanwhile
      c.waiting_ring = false;
      std::string_view body{ring_buf.data() + 5, (size_t)len - 5};
      int http_code = 200;
      if (status == 0) {
        respond(c, 200, "OK", body);
      } else {
        // body is {"status": {"code": N, ...}} from the Python engine —
        // surface the engine's own status code (400 vs 500 matters)
        http_code = 500;
        JDoc doc;
        if (json_parse(body.data(), body.size(), doc) &&
            doc.nodes[0].type == JValue::Obj) {
          if (auto* st = doc.get(doc.nodes[0], "status"))
            if (auto* code = doc.get(*st, "code")) {
              int parsed = (int)jnum(*code);
              if (parsed >= 400 && parsed < 600) http_code = parsed;
            }
        }
        const char* text = http_code == 400 ? "Bad Request"
                           : http_code == 503 ? "Service Unavailable"
                                              : "Internal Server Error";
        respond(c, http_code, text, body);
      }
      metrics.observe_api(rp.is_feedback ? "feedback" : "predictions",
                          http_code, 1e-9 * (now_ns() - rp.started_ns));
      flush_out(c);
      if (c.fd >= 0 && c.in.size() > 0) process_in(c);  // pipelined requests
    }
    // Engine gone or stalled: time out waiters so connections don't hang and
    // the poll timer doesn't spin forever.
    uint64_t now = now_ns();
    for (auto it = pending.begin(); it != pending.end();) {
      if (now - it->second.started_ns < kRingTimeoutNs) {
        ++it;
        continue;
      }
      RingPending rp = it->second;
      it = pending.erase(it);
      Conn& c = conn(rp.conn_fd);
      if (c.fd == rp.conn_fd && c.gen == rp.conn_gen) {
        c.waiting_ring = false;
        respond_error(c, 504, "ENGINE_TIMEOUT", "engine did not answer within deadline");
        metrics.observe_api(rp.is_feedback ? "feedback" : "predictions", 504,
                            1e-9 * (now - rp.started_ns));
        flush_out(c);
      }
    }
    {
      // device requests time out as a unit (dedupe multi-site execs first)
      std::vector<DevExec*> expired;
      for (auto& [rid, entry] : pending_dev) {
        DevExec* st = entry.first;
        if (now - st->t0 < kRingTimeoutNs) continue;
        bool seen = false;
        for (auto* e : expired)
          if (e == st) seen = true;
        if (!seen) expired.push_back(st);
      }
      for (DevExec* st : expired) {
        Conn& c = conn(st->conn_fd);
        if (c.fd == st->conn_fd && c.gen == st->conn_gen) {
          if (st->is_grpc) {
            grpc_trailers_error(c, st->h2_sid, 4,
                                "engine did not answer within deadline");
          } else {
            c.waiting_ring = false;
            respond_error(c, 504, "ENGINE_TIMEOUT",
                          "engine did not answer within deadline");
          }
          metrics.observe_api("predictions", 504, 1e-9 * (now - st->t0));
          flush_out(c);
        }
        drop_dev_exec(st);
      }
    }
    for (auto it2 = pending_grpc.begin(); it2 != pending_grpc.end();) {
      if (now - it2->second.started_ns < kRingTimeoutNs) {
        ++it2;
        continue;
      }
      GrpcPending gp = it2->second;
      it2 = pending_grpc.erase(it2);
      Conn& c = conn(gp.conn_fd);
      if (c.fd == gp.conn_fd && c.gen == gp.conn_gen) {
        grpc_trailers_error(c, gp.sid, 4, "engine did not answer within deadline");
        metrics.observe_api(gp.is_feedback ? "feedback" : "predictions", 504,
                            1e-9 * (now - gp.started_ns));
        flush_out(c);
      }
    }
    if (pending.empty() && pending_dev.empty() && pending_grpc.empty())
      disarm_timer();
  }

  // ---- request routing ----
  void dispatch(Conn& c, std::string_view method, std::string_view path,
                std::string_view body) {
    uint64_t t0 = now_ns();
    if (path == "/api/v0.1/predictions" || path == "/predict") {
      if (method != "POST") return respond_error(c, 405, "METHOD_NOT_ALLOWED", "use POST");
      return handle_predictions(c, body, t0);
    }
    if (path == "/api/v0.1/feedback" || path == "/send-feedback") {
      if (method != "POST") return respond_error(c, 405, "METHOD_NOT_ALLOWED", "use POST");
      return handle_feedback(c, body, t0);
    }
    if (path == "/ready") {
      if (paused) return respond(c, 503, "Service Unavailable", "not ready", "text/plain; charset=utf-8");
      return respond(c, 200, "OK", "ready", "text/plain; charset=utf-8");
    }
    if (path == "/live") return respond(c, 200, "OK", "live", "text/plain; charset=utf-8");
    if (path == "/ping") return respond(c, 200, "OK", "pong", "text/plain; charset=utf-8");
    if (path == "/pause") {
      paused = true;
      return respond(c, 200, "OK", "paused", "text/plain; charset=utf-8");
    }
    if (path == "/unpause") {
      paused = false;
      return respond(c, 200, "OK", "unpaused", "text/plain; charset=utf-8");
    }
    if (path == "/metrics" || path == "/prometheus") {
      Buf b;
      metrics.expose(b);
      // bandit router state (metrics/registry.py exposes the same figures as
      // bandit_branch_{i}_mean_reward gauges on the Python engine)
      bool first = true;
      for (auto& u : prog.units) {
        if (!is_bandit(u.kind)) continue;
        if (first) {
          b.append("# TYPE bandit_branch_mean_reward gauge\n");
          b.append("# TYPE bandit_branch_pulls_total counter\n");
          first = false;
        }
        for (int i = 0; i < u.n_branches; ++i) {
          b.append("bandit_branch_mean_reward{router=\"");
          b.append(u.name);
          b.append("\",branch=\"");
          b.append_i64(i);
          b.append("\"} ");
          b.append_double(u.reward_sum[i] / (double)(u.pulls[i] ? u.pulls[i] : 1));
          b.push('\n');
          b.append("bandit_branch_pulls_total{router=\"");
          b.append(u.name);
          b.append("\",branch=\"");
          b.append_i64(i);
          b.append("\"} ");
          b.append_u64(u.pulls[i]);
          b.push('\n');
        }
      }
      return respond(c, 200, "OK", {b.data(), b.size()}, "text/plain; charset=utf-8");
    }
    if (path == "/seldon.json" && !openapi.empty())
      return respond(c, 200, "OK", openapi);
    respond_error(c, 404, "NOT_FOUND", "no such route");
  }

  // ------------------------------------------------------------------
  // HTTP/2 + gRPC (external API parity: grpc/SeldonGrpcServer.java,
  // Seldon.Predict / Seldon.SendFeedback)
  // ------------------------------------------------------------------

  // Constant response fragments, built once in init_grpc_constants().
  std::string ndarray_row_bytes;   // one ListValue.values entry (a 3-number row)
  std::string tensor_row_bytes;    // 3 LE doubles
  std::string h2_resp_headers;     // :status 200 + content-type application/grpc
  std::string h2_trailers_ok;      // grpc-status: 0

  void init_grpc_constants() {
    const double vals[3] = {(double)(float)0.1, (double)(float)0.9, 0.5};
    Buf num;  // three Value{number_value} entries wrapped as ListValue.values
    for (double v : vals) {
      Buf inner;
      PbWriter iw{inner};
      iw.tag(2, 1);
      iw.fixed64_raw(v);
      PbWriter nw{num};
      nw.tag(1, 2);
      nw.varint(inner.size());
      num.append(inner.data(), inner.size());
    }
    Buf row;  // Value{list_value = ListValue{the three numbers}}
    PbWriter rw{row};
    rw.tag(6, 2);
    rw.varint(num.size());
    row.append(num.data(), num.size());
    Buf entry;  // ListValue.values entry holding the row Value
    PbWriter ew{entry};
    ew.tag(1, 2);
    ew.varint(row.size());
    entry.append(row.data(), row.size());
    ndarray_row_bytes.assign(entry.data(), entry.size());
    tensor_row_bytes.assign((const char*)vals, 24);

    h2_resp_headers.push_back((char)0x88);  // :status 200 (static 8)
    // content-type (static name 31), literal without indexing
    h2_resp_headers.push_back((char)0x0f);
    h2_resp_headers.push_back((char)0x10);
    h2_resp_headers.push_back((char)16);
    h2_resp_headers += "application/grpc";
    // grpc-status: 0 trailer, literal without indexing, new name
    h2_trailers_ok.push_back((char)0x00);
    h2_trailers_ok.push_back((char)11);
    h2_trailers_ok += "grpc-status";
    h2_trailers_ok.push_back((char)1);
    h2_trailers_ok += "0";
  }

  void h2_frame(Buf& out, uint8_t type, uint8_t flags, uint32_t sid,
                std::string_view payload) {
    uint32_t len = (uint32_t)payload.size();
    char hdr[9] = {(char)(len >> 16), (char)(len >> 8), (char)len,
                   (char)type, (char)flags,
                   (char)(sid >> 24), (char)(sid >> 16), (char)(sid >> 8), (char)sid};
    out.append(hdr, 9);
    out.append(payload);
  }

  void h2_begin(Conn& c) {
    c.is_h2 = true;
    c.h2 = std::make_unique<H2State>();
    h2_frame(c.outbuf, 4, 0, 0, {});  // server SETTINGS (defaults)
  }

  void grpc_trailers_error(Conn& c, uint32_t sid, int grpc_code, std::string_view msg) {
    Buf headers;
    headers.append(h2_resp_headers);
    h2_frame(c.outbuf, 1, 0x4, sid, {headers.data(), headers.size()});
    Buf tr;
    char code_str[8];
    int n = snprintf(code_str, sizeof(code_str), "%d", grpc_code);
    tr.push((char)0x00);
    tr.push((char)11);
    tr.append("grpc-status");
    tr.push((char)n);
    tr.append(code_str, n);
    if (!msg.empty() && msg.size() < 120) {
      tr.push((char)0x00);
      tr.push((char)12);
      tr.append("grpc-message");
      tr.push((char)msg.size());
      tr.append(msg);
    }
    h2_frame(c.outbuf, 1, 0x5, sid, {tr.data(), tr.size()});  // END_HEADERS|END_STREAM
  }

  void grpc_respond_msg(Conn& c, uint32_t sid, std::string_view msg) {
    h2_frame(c.outbuf, 1, 0x4, sid, h2_resp_headers);
    H2Blocked item;
    item.sid = sid;
    item.data.reserve(msg.size() + 5);
    item.data.push_back((char)0);  // uncompressed
    char len4[4] = {(char)(msg.size() >> 24), (char)(msg.size() >> 16),
                    (char)(msg.size() >> 8), (char)msg.size()};
    item.data.append(len4, 4);
    item.data.append(msg);
    item.stream_window = c.h2->client_initial_window;
    auto credit = c.h2->stream_credit.find(sid);
    if (credit != c.h2->stream_credit.end()) {
      item.stream_window += credit->second;
      c.h2->stream_credit.erase(credit);
    }
    c.h2->blocked.emplace_back(std::move(item));
    h2_drain_blocked(c);
  }

  // Emit as much queued DATA as the connection + per-stream send windows
  // allow, in frames no larger than the peer's SETTINGS_MAX_FRAME_SIZE;
  // trailers follow the last DATA chunk of each response. A stream whose
  // window is exhausted doesn't block responses on other streams.
  void h2_drain_blocked(Conn& c) {
    for (auto it = c.h2->blocked.begin(); it != c.h2->blocked.end();) {
      H2Blocked& b = *it;
      while (b.off < b.data.size() && c.h2->send_window > 0 && b.stream_window > 0) {
        size_t allowed = (size_t)std::min(c.h2->send_window, b.stream_window);
        size_t chunk = std::min({b.data.size() - b.off,
                                 (size_t)c.h2->client_max_frame, allowed});
        h2_frame(c.outbuf, 0, 0, b.sid, {b.data.data() + b.off, chunk});
        b.off += chunk;
        c.h2->send_window -= (int64_t)chunk;
        b.stream_window -= (int64_t)chunk;
      }
      if (b.off == b.data.size()) {
        h2_frame(c.outbuf, 1, 0x5, b.sid, h2_trailers_ok);
        it = c.h2->blocked.erase(it);
      } else if (c.h2->send_window <= 0) {
        break;
      } else {
        ++it;
      }
    }
  }

  // Build the Predict response proto for a parsed request.
  void grpc_build_response(const PbSeldonMsg& req, const ExecOut& ex,
                           const Payload& result, Kind owner, Buf& msg) {
    Buf meta;
    PbWriter mw{meta};
    if (!req.puid.empty()) {
      mw.str(1, req.puid);
    } else {
      char puid[33];
      rng.puid_hex(puid);
      mw.str(1, {puid, 32});
    }
    // Bandit router tags FIRST (for tags the request wins on key collision —
    // engine _merge_meta target-wins — and protobuf map decoding keeps the
    // LAST duplicate entry, so echoed request tags override these).
    if (!ex.bandit_tags.empty()) {
      const Unit& bu = prog.units[ex.bandit_tags[0].first];
      {
        Buf val;  // Value{string_value = class}
        PbWriter vw{val};
        vw.str(3, kind_class(bu.kind));
        Buf e;
        PbWriter ew{e};
        ew.str(1, "bandit");
        ew.tag(2, 2);
        ew.varint(val.size());
        e.append(val.data(), val.size());
        mw.tag(2, 2);
        mw.varint(e.size());
        meta.append(e.data(), e.size());
      }
      {
        Buf lv;  // ListValue{values: Value{number_value}}
        for (double m : ex.bandit_tags[0].second) {
          Buf num;
          PbWriter nw{num};
          nw.tag(2, 1);
          nw.fixed64_raw(nearbyint(m * 1e6) / 1e6);
          PbWriter lw{lv};
          lw.tag(1, 2);
          lw.varint(num.size());
          lv.append(num.data(), num.size());
        }
        Buf val;  // Value{list_value = ListValue}
        PbWriter vw{val};
        vw.tag(6, 2);
        vw.varint(lv.size());
        val.append(lv.data(), lv.size());
        Buf e;
        PbWriter ew{e};
        ew.str(1, "branch_means");
        ew.tag(2, 2);
        ew.varint(val.size());
        e.append(val.data(), val.size());
        mw.tag(2, 2);
        mw.varint(e.size());
        meta.append(e.data(), e.size());
      }
    }
    // Echoed request meta first, computed entries after: for duplicate map
    // keys protobuf keeps the LAST entry, which makes computed values win —
    // the proto twin of the Python engine's setdefault/overwrite semantics.
    for (auto sv : req.meta_echo) meta.append(sv);
    for (auto& [name, branch] : ex.routing) {
      Buf e;
      PbWriter ew{e};
      ew.str(1, name);
      ew.tag(2, 0);
      ew.varint((uint64_t)branch);
      mw.tag(3, 2);
      mw.varint(e.size());
      meta.append(e.data(), e.size());
    }
    for (auto& [name, cls] : ex.path) {
      Buf e;
      PbWriter ew{e};
      ew.str(1, name);
      ew.str(2, cls);
      mw.tag(4, 2);
      mw.varint(e.size());
      meta.append(e.data(), e.size());
    }
    // metrics: owner's triplet, echoed request metrics, remaining units
    auto emit_triplet = [&]() {
      struct M { const char* key; int type; float value; };
      static const M kMs[3] = {{"mycounter", 0, 1.0f}, {"mygauge", 1, 100.0f},
                               {"mytimer", 2, 20.6f}};
      for (auto& m : kMs) {
        Buf e;
        PbWriter ew{e};
        ew.str(1, m.key);
        if (m.type != 0) {
          ew.tag(2, 0);
          ew.varint((uint64_t)m.type);
        }
        ew.fixed32(3, m.value);
        mw.tag(5, 2);
        mw.varint(e.size());
        meta.append(e.data(), e.size());
      }
    };
    int remaining = ex.model_visits;
    if (owner != Kind::AverageCombiner && remaining > 0) {
      emit_triplet();
      --remaining;
    }
    for (auto sv : req.req_metrics_raw) meta.append(sv);
    for (int i = 0; i < remaining; ++i) emit_triplet();

    PbWriter w{msg};
    w.tag(2, 2);
    w.varint(meta.size());
    msg.append(meta.data(), meta.size());

    if (result.kind == PKind::Str) {
      w.str(5, result.echo);
    } else if (result.kind == PKind::Bin) {
      w.str(4, result.echo);
    } else if (result.kind == PKind::NDArray || result.kind == PKind::Tensor) {
      Buf dd;
      PbWriter dw{dd};
      if (owner == Kind::AverageCombiner) {
        dw.str(1, "t:0");
        dw.str(1, "t:1");
        dw.str(1, "t:2");
      } else {
        dw.str(1, "class0");
        dw.str(1, "class1");
        dw.str(1, "class2");
      }
      if (result.kind == PKind::NDArray) {
        Buf lv;
        for (int64_t i = 0; i < result.rows; ++i) lv.append(ndarray_row_bytes);
        dw.tag(3, 2);
        dw.varint(lv.size());
        dd.append(lv.data(), lv.size());
      } else {
        Buf t;
        PbWriter tw{t};
        Buf shape;
        PbWriter sw{shape};
        sw.varint((uint64_t)result.rows);
        sw.varint(3);
        tw.tag(1, 2);
        tw.varint(shape.size());
        t.append(shape.data(), shape.size());
        tw.tag(2, 2);
        tw.varint((uint64_t)result.rows * 24);
        for (int64_t i = 0; i < result.rows; ++i) t.append(tensor_row_bytes);
        dw.tag(2, 2);
        dw.varint(t.size());
        dd.append(t.data(), t.size());
      }
      w.tag(3, 2);
      w.varint(dd.size());
      msg.append(dd.data(), dd.size());
    }
  }

  void h2_rpc(Conn& c, uint32_t sid, H2Stream& s) {
    uint64_t t0 = now_ns();
    bool is_predict = s.path == "/seldon.protos.Seldon/Predict" ||
                      s.path == "/seldon.protos.Model/Predict";
    bool is_feedback = s.path == "/seldon.protos.Seldon/SendFeedback" ||
                       s.path == "/seldon.protos.Model/SendFeedback";
    const char* method = is_feedback ? "feedback" : "predictions";
    if (s.path_huffman) {
      grpc_trailers_error(c, sid, 12, "huffman-coded :path not supported");
      return;
    }
    if (!is_predict && !is_feedback) {
      grpc_trailers_error(c, sid, 12, "unknown method");
      return;
    }
    if (paused) {
      grpc_trailers_error(c, sid, 14, "paused");
      metrics.observe_api(method, 503, 1e-9 * (now_ns() - t0));
      return;
    }
    if (!is_feedback && overloaded()) {
      ++metrics.shed_total;
      grpc_trailers_error(c, sid, 8,  // RESOURCE_EXHAUSTED
                          "in-flight request limit reached; retry later");
      metrics.observe_api(method, 429, 1e-9 * (now_ns() - t0));
      return;
    }
    std::string_view data{s.data.data(), s.data.size()};
    if (data.size() < 5 || data[0] != 0) {
      grpc_trailers_error(c, sid, 13, "bad gRPC frame");
      metrics.observe_api(method, 500, 1e-9 * (now_ns() - t0));
      return;
    }
    uint32_t mlen = ((uint8_t)data[1] << 24) | ((uint8_t)data[2] << 16) |
                    ((uint8_t)data[3] << 8) | (uint8_t)data[4];
    if (data.size() < 5 + (size_t)mlen) {
      grpc_trailers_error(c, sid, 13, "truncated gRPC frame");
      metrics.observe_api(method, 500, 1e-9 * (now_ns() - t0));
      return;
    }
    std::string_view body = data.substr(5, mlen);

    // Graphs the edge can't execute natively ride the ring as full proto
    // frames (kind 3 predict / kind 4 feedback): the engine process answers
    // with proto bytes, so gRPC serves EVERY graph on this port — the
    // reference's engine serves any graph over gRPC too
    // (grpc/SeldonService.java:44-79).
    if (!prog.native) {
      forward_ring_grpc(c, sid, is_feedback ? 4 : 3, body, t0);
      return;
    }

    if (prog.has_device && !is_feedback) {
      // Native device plane for tensor payloads (feedback stays native —
      // bandit state lives here); names/ndarray/bin/str/json payloads go
      // kind-3 so the Python engine keeps exact semantics.
      handle_grpc_device(c, sid, body, t0);
      return;
    }

    if (is_feedback) {
      // Feedback{request=1, response=2, reward=3 float, truth=4}; the
      // response's meta.routing drives the bandit update + replay branch.
      PbReader r{(const uint8_t*)body.data(), (const uint8_t*)body.data() + body.size()};
      float reward = 0;
      std::vector<std::pair<std::string_view, int>> routing_entries;
      uint32_t field, wire;
      while (r.p + 1 <= r.end && r.tag(field, wire)) {
        if (field == 3 && wire == 5 && r.end - r.p >= 4) {
          memcpy(&reward, r.p, 4);
          r.p += 4;
        } else if (field == 2 && wire == 2) {  // response SeldonMessage
          std::string_view resp_span;
          if (!r.len_span(resp_span)) break;
          PbReader rr{(const uint8_t*)resp_span.data(),
                      (const uint8_t*)resp_span.data() + resp_span.size()};
          uint32_t rf, rw2;
          while (rr.p < rr.end && rr.tag(rf, rw2)) {
            if (rf == 2 && rw2 == 2) {  // Meta
              std::string_view meta_span;
              if (!rr.len_span(meta_span)) break;
              PbReader mr{(const uint8_t*)meta_span.data(),
                          (const uint8_t*)meta_span.data() + meta_span.size()};
              uint32_t mf, mw2;
              while (mr.p < mr.end && mr.tag(mf, mw2)) {
                if (mf == 3 && mw2 == 2) {  // routing map entry
                  std::string_view entry;
                  if (!mr.len_span(entry)) break;
                  PbReader er{(const uint8_t*)entry.data(),
                              (const uint8_t*)entry.data() + entry.size()};
                  std::string_view key;
                  uint64_t branch = 0;
                  uint32_t ef, ew2;
                  while (er.p < er.end && er.tag(ef, ew2)) {
                    if (ef == 1 && ew2 == 2) {
                      if (!er.len_span(key)) break;
                    } else if (ef == 2 && ew2 == 0) {
                      if (!er.varint(branch)) break;
                    } else if (!er.skip(ew2)) {
                      break;
                    }
                  }
                  if (!key.empty()) routing_entries.push_back({key, (int)(int64_t)branch});
                } else if (!mr.skip(mw2)) {
                  break;
                }
              }
            } else if (!rr.skip(rw2)) {
              break;
            }
          }
        } else if (!r.skip(wire)) {
          break;
        }
      }
      if (prog.native && !feedback_walk(prog.root, routing_entries, reward)) {
        grpc_trailers_error(c, sid, 3,
                            "Feedback routing names a branch outside the unit's children");
        metrics.observe_api(method, 400, 1e-9 * (now_ns() - t0));
        return;
      }
      ++metrics.feedback_events;
      if (reward != 0) metrics.feedback_reward += reward < 0 ? -reward : reward;
      Buf msg;  // SeldonMessage{meta: {}} — REST parity ({"meta": {}})
      PbWriter w{msg};
      w.tag(2, 2);
      w.varint(0);
      grpc_respond_msg(c, sid, {msg.data(), msg.size()});
      metrics.observe_api(method, 200, 1e-9 * (now_ns() - t0));
      return;
    }

    PbSeldonMsg req;
    if (!pb_parse_seldon_message(body, req)) {
      grpc_trailers_error(c, sid, 3, "cannot parse SeldonMessage");
      metrics.observe_api(method, 400, 1e-9 * (now_ns() - t0));
      return;
    }
    if (req.in.kind == PKind::Tensor && req.tensor_prod != req.tensor_nvals) {
      grpc_trailers_error(c, sid, 3, "tensor values do not fit shape");
      metrics.observe_api(method, 400, 1e-9 * (now_ns() - t0));
      return;
    }
    ExecOut ex;
    Payload result;
    Kind owner;
    if (!eval_unit(prog, prog.root, rng, req.in, ex, result, owner)) {
      grpc_trailers_error(c, sid, ex.err_code == 400 ? 3 : 13, ex.err_info);
      metrics.observe_api(method, ex.err_code, 1e-9 * (now_ns() - t0));
      return;
    }
    Buf msg;
    grpc_build_response(req, ex, result, owner, msg);
    grpc_respond_msg(c, sid, {msg.data(), msg.size()});
    metrics.mycounter += ex.model_visits;
    if (ex.model_visits) {
      metrics.mygauge = 100.0;
      for (int i = 0; i < ex.model_visits; ++i) metrics.mytimer.observe(20.6 / 1000.0);
      metrics.custom_seen += ex.model_visits;
    }
    metrics.observe_api(method, 200, 1e-9 * (now_ns() - t0));
  }

  // Frame loop; consumes complete frames from c.in.
  void h2_process(Conn& c) {
    size_t off = 0;
    std::string_view data{c.in.data(), c.in.size()};
    for (;;) {
      if (data.size() - off < 9) break;
      const uint8_t* h = (const uint8_t*)data.data() + off;
      uint32_t len = (h[0] << 16) | (h[1] << 8) | h[2];
      uint8_t type = h[3], flags = h[4];
      uint32_t sid = ((h[5] & 0x7f) << 24) | (h[6] << 16) | (h[7] << 8) | h[8];
      if (len > (1u << 24)) {
        close_conn(c);
        return;
      }
      if (data.size() - off < 9 + len) break;
      std::string_view payload = data.substr(off + 9, len);
      off += 9 + len;
      switch (type) {
        case 0: {  // DATA
          auto it = c.h2->streams.find(sid);
          if (flags & 0x8) {  // PADDED
            if (payload.empty() || (size_t)(uint8_t)payload[0] > payload.size() - 1) {
              close_conn(c);  // RFC 7540 §6.1: PROTOCOL_ERROR
              return;
            }
            uint8_t pad = (uint8_t)payload[0];
            payload = payload.substr(1, payload.size() - 1 - pad);
          }
          c.h2->recv_unacked += len;
          if (it != c.h2->streams.end()) {
            H2Stream& s = it->second;
            if (s.data.size() + payload.size() > kMaxBody) {
              // cap what a stream may buffer (REST-path parity): refuse the
              // RPC instead of growing without bound on granted window
              grpc_trailers_error(c, sid, 8, "request message too large");
              // RFC 7540 §8.1: responding before the full request arrived —
              // RST_STREAM(NO_ERROR) tells the peer to stop sending
              char rst[4] = {0, 0, 0, 0};
              h2_frame(c.outbuf, 3, 0, sid, {rst, 4});
              c.h2->streams.erase(it);
              c.h2->stream_credit.erase(sid);
              break;
            }
            s.data.append(payload);
            if (flags & 0x1) {  // END_STREAM
              h2_rpc(c, sid, s);
              c.h2->streams.erase(it);
              c.h2->stream_credit.erase(sid);
            } else {
              // replenish this stream's recv window so bodies larger than
              // the 64KB initial window keep flowing; coalesced like the
              // connection-level grant below
              s.recv_unacked += len;
              if (s.recv_unacked >= (1u << 15)) {
                uint32_t inc = s.recv_unacked;
                char wu[4] = {(char)(inc >> 24), (char)(inc >> 16),
                              (char)(inc >> 8), (char)inc};
                h2_frame(c.outbuf, 8, 0, sid, {wu, 4});
                s.recv_unacked = 0;
              }
            }
          }
          break;
        }
        case 1: {  // HEADERS
          if (flags & 0x8) {  // PADDED
            if (payload.empty() || (size_t)(uint8_t)payload[0] > payload.size() - 1) {
              close_conn(c);
              return;
            }
            uint8_t pad = (uint8_t)payload[0];
            payload = payload.substr(1, payload.size() - 1 - pad);
          }
          if (flags & 0x20) {  // PRIORITY
            if (payload.size() < 5) break;
            payload = payload.substr(5);
          }
          if (!(flags & 0x4)) {  // no END_HEADERS: CONTINUATION unsupported
            close_conn(c);
            return;
          }
          std::vector<HpackField> fields;
          if (!hpack_decode((const uint8_t*)payload.data(),
                            (const uint8_t*)payload.data() + payload.size(),
                            c.h2->hpack, fields)) {
            close_conn(c);
            return;
          }
          H2Stream& s = c.h2->streams[sid];
          for (auto& f : fields) {
            if (f.name == ":path") {
              s.path = f.value;
              s.path_huffman = f.value_huffman;
            }
          }
          if (flags & 0x1) {  // END_STREAM with no body
            h2_rpc(c, sid, s);
            c.h2->streams.erase(sid);
            c.h2->stream_credit.erase(sid);
          }
          break;
        }
        case 3:  // RST_STREAM
          c.h2->streams.erase(sid);
          c.h2->stream_credit.erase(sid);
          for (auto it = c.h2->blocked.begin(); it != c.h2->blocked.end();) {
            it = it->sid == sid ? c.h2->blocked.erase(it) : std::next(it);
          }
          break;
        case 4:  // SETTINGS
          if (!(flags & 0x1)) {
            for (size_t i = 0; i + 6 <= payload.size(); i += 6) {
              const uint8_t* e = (const uint8_t*)payload.data() + i;
              uint16_t id = (uint16_t)((e[0] << 8) | e[1]);
              uint32_t val = ((uint32_t)e[2] << 24) | (e[3] << 16) | (e[4] << 8) | e[5];
              if (id == 4) {  // INITIAL_WINDOW_SIZE
                if (val > 0x7fffffffu) { close_conn(c); return; }
                // RFC 7540 §6.9.2: delta applies to existing stream windows
                int64_t delta = (int64_t)val - c.h2->client_initial_window;
                c.h2->client_initial_window = (int64_t)val;
                for (auto& b : c.h2->blocked) b.stream_window += delta;
                if (delta > 0) h2_drain_blocked(c);
              } else if (id == 5) {  // MAX_FRAME_SIZE
                if (val >= 16384 && val <= 16777215) c.h2->client_max_frame = val;
              }
            }
            h2_frame(c.outbuf, 4, 0x1, 0, {});
          }
          break;
        case 6:  // PING
          if (!(flags & 0x1)) h2_frame(c.outbuf, 6, 0x1, 0, payload);
          break;
        case 7:  // GOAWAY
          c.want_close = true;
          break;
        case 8: {  // WINDOW_UPDATE
          if (payload.size() == 4) {
            uint32_t inc = ((uint8_t)payload[0] << 24) | ((uint8_t)payload[1] << 16) |
                           ((uint8_t)payload[2] << 8) | (uint8_t)payload[3];
            inc &= 0x7fffffff;
            if (sid == 0) {
              c.h2->send_window += inc;
            } else {
              bool queued = false;
              for (auto& b : c.h2->blocked) {
                if (b.sid == sid) {
                  b.stream_window += inc;
                  queued = true;
                }
              }
              // grant arrived before the response was queued: bank it for
              // grpc_respond_msg (only for streams we know about, so bogus
              // sids can't grow the map)
              if (!queued && c.h2->streams.count(sid)) {
                c.h2->stream_credit[sid] += inc;
              }
            }
            h2_drain_blocked(c);
          }
          break;
        }
        default:
          break;  // ignore unknown frames
      }
      if (c.fd < 0) return;
    }
    if (off > 0) {
      size_t remaining = data.size() - off;
      if (remaining > 0) memmove(c.in.v.data(), c.in.v.data() + off, remaining);
      c.in.v.resize(remaining);
    }
    if (c.h2->recv_unacked >= (1u << 15)) {
      char wu[4] = {(char)(c.h2->recv_unacked >> 24), (char)(c.h2->recv_unacked >> 16),
                    (char)(c.h2->recv_unacked >> 8), (char)c.h2->recv_unacked};
      h2_frame(c.outbuf, 8, 0, 0, {wu, 4});
      c.h2->recv_unacked = 0;
    }
    flush_out(c);
  }

  // ---- connection I/O ----
  void flush_out(Conn& c) {
    if (defer_flush) {
      // ring-drain pass in progress: queue one coalesced flush per
      // connection instead of one send() per response — with 8 gRPC
      // streams per connection a single drain batch would otherwise issue
      // up to 8 syscalls where one suffices
      if (!c.flush_pending) {
        c.flush_pending = true;
        flush_queue.push_back(c.fd);
      }
      return;
    }
    while (c.out_off < c.outbuf.size()) {
      ssize_t n = ::send(c.fd, c.outbuf.data() + c.out_off,
                         c.outbuf.size() - c.out_off, MSG_NOSIGNAL);
      if (n > 0) {
        c.out_off += (size_t)n;
        continue;
      }
      if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        epoll_event ev{};
        ev.events = EPOLLIN | EPOLLOUT;
        ev.data.fd = c.fd;
        epoll_ctl(epfd, EPOLL_CTL_MOD, c.fd, &ev);
        return;
      }
      close_conn(c);
      return;
    }
    c.outbuf.clear();
    c.out_off = 0;
    if (c.want_close) close_conn(c);
  }

  void close_conn(Conn& c) {
    if (c.fd < 0) return;
    if (defer_flush && c.flush_pending && c.out_off < c.outbuf.size()) {
      // a completed response is parked for the end-of-drain flush; send it
      // best-effort before closing (pre-deferral behaviour: responses were
      // flushed synchronously ahead of whatever closes the connection)
      defer_flush = false;
      c.flush_pending = false;
      flush_out(c);  // may itself close on send error
      defer_flush = true;
      if (c.fd < 0) return;
    }
    epoll_ctl(epfd, EPOLL_CTL_DEL, c.fd, nullptr);
    ::close(c.fd);
    c.fd = -1;
    ++c.gen;
    c.in.clear();
    c.outbuf.clear();
    c.out_off = 0;
    c.want_close = false;
    c.waiting_ring = false;
    c.is_h2 = false;
    c.flush_pending = false;  // never leak the queued-flush mark to a
                              // reused fd (a stale true would swallow the
                              // new connection's first deferred flush)
    c.h2.reset();
  }

  // Try to parse and handle complete requests in c.in; returns when more
  // bytes are needed.
  void process_in(Conn& c) {
    if (c.is_h2) {
      h2_process(c);
      return;
    }
    // HTTP/2 connection preface?
    if (c.in.size() >= 24 &&
        memcmp(c.in.data(), "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n", 24) == 0) {
      size_t remaining = c.in.size() - 24;
      if (remaining > 0) memmove(c.in.v.data(), c.in.v.data() + 24, remaining);
      c.in.v.resize(remaining);
      h2_begin(c);
      h2_process(c);
      return;
    }
    if (c.in.size() > 0 && c.in.size() < 24 && memcmp(c.in.data(), "PRI ",
                                                     c.in.size() < 4 ? c.in.size() : 4) == 0)
      return;  // wait for the full preface
    for (;;) {
      if (c.waiting_ring) return;  // one request at a time when ring-pending
      std::string_view data{c.in.data(), c.in.size()};
      size_t hdr_end = data.find("\r\n\r\n");
      if (hdr_end == std::string_view::npos) {
        if (data.size() > (1u << 20)) close_conn(c);
        return;
      }
      std::string_view head = data.substr(0, hdr_end);
      size_t line_end = head.find("\r\n");
      std::string_view req_line = head.substr(0, line_end == std::string_view::npos ? head.size() : line_end);
      size_t sp1 = req_line.find(' ');
      size_t sp2 = req_line.rfind(' ');
      if (sp1 == std::string_view::npos || sp2 == sp1) {
        close_conn(c);
        return;
      }
      std::string_view method = req_line.substr(0, sp1);
      std::string_view target = req_line.substr(sp1 + 1, sp2 - sp1 - 1);
      size_t q = target.find('?');
      std::string_view path = q == std::string_view::npos ? target : target.substr(0, q);
      // headers we care about
      // (body cap: kMaxBody, shared with the gRPC stream buffer cap)
      uint64_t content_len = 0;
      bool close_hdr = false;
      bool chunked = false;
      size_t pos = line_end == std::string_view::npos ? head.size() : line_end + 2;
      while (pos < head.size()) {
        size_t eol = head.find("\r\n", pos);
        std::string_view line = head.substr(pos, (eol == std::string_view::npos ? head.size() : eol) - pos);
        pos = eol == std::string_view::npos ? head.size() : eol + 2;
        size_t colon = line.find(':');
        if (colon == std::string_view::npos) continue;
        std::string_view name = line.substr(0, colon);
        std::string_view value = line.substr(colon + 1);
        while (!value.empty() && value.front() == ' ') value.remove_prefix(1);
        if (name.size() == 14 && strncasecmp(name.data(), "content-length", 14) == 0)
          content_len = strtoull(std::string(value).c_str(), nullptr, 10);
        else if (name.size() == 10 && strncasecmp(name.data(), "connection", 10) == 0)
          close_hdr = value.size() == 5 && strncasecmp(value.data(), "close", 5) == 0;
        else if (name.size() == 17 && strncasecmp(name.data(), "transfer-encoding", 17) == 0) {
          // only "chunked" (possibly last in a list, any case) changes body
          // framing; "identity" with a Content-Length is a normal request
          for (size_t ti = 0; ti + 7 <= value.size(); ++ti) {
            if (strncasecmp(value.data() + ti, "chunked", 7) == 0) {
              chunked = true;
              break;
            }
          }
        }
      }
      if (chunked) {
        c.want_close = true;
        respond_error(c, 501, "NOT_IMPLEMENTED", "chunked transfer encoding not supported");
        flush_out(c);
        return;
      }
      if (content_len > kMaxBody) {
        c.want_close = true;
        respond_error(c, 413, "PAYLOAD_TOO_LARGE", "request body exceeds 1GB limit");
        flush_out(c);
        return;
      }
      size_t total = hdr_end + 4 + (size_t)content_len;
      if (data.size() < total) return;  // need more body bytes
      std::string_view body = data.substr(hdr_end + 4, content_len);
      c.want_close = close_hdr;
      dispatch(c, method, path, body);
      // consume the request
      size_t remaining = data.size() - total;
      if (remaining > 0) memmove(c.in.v.data(), c.in.v.data() + total, remaining);
      c.in.v.resize(remaining);
      if (!c.waiting_ring) flush_out(c);
      if (c.fd < 0) return;
      if (remaining == 0) return;
    }
  }

  void on_readable(Conn& c) {
    char tmp[65536];
    for (;;) {
      ssize_t n = ::recv(c.fd, tmp, sizeof(tmp), 0);
      if (n > 0) {
        c.in.append(tmp, (size_t)n);
        if (n < (ssize_t)sizeof(tmp)) break;
        continue;
      }
      if (n == 0) {
        close_conn(c);
        return;
      }
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      close_conn(c);
      return;
    }
    process_in(c);
  }

  int make_listener(const char* host, int port) {
    int lfd = ::socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    int one = 1;
    setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    setsockopt(lfd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)port);
    addr.sin_addr.s_addr = INADDR_ANY;
    if (host) {
      addrinfo hints{}, *res = nullptr;
      hints.ai_family = AF_INET;
      if (getaddrinfo(host, nullptr, &hints, &res) != 0 || !res) {
        fprintf(stderr, "cannot resolve host %s\n", host);
        return -1;
      }
      addr.sin_addr = ((sockaddr_in*)res->ai_addr)->sin_addr;
      freeaddrinfo(res);
    }
    if (bind(lfd, (sockaddr*)&addr, sizeof(addr)) != 0 || listen(lfd, 1024) != 0) {
      perror("bind/listen");
      ::close(lfd);
      return -1;
    }
    return lfd;
  }

  int run(const char* host, int port, int grpc_port) {
    signal(SIGPIPE, SIG_IGN);
    int lfd = make_listener(host, port);
    if (lfd < 0) return 1;
    int gfd = grpc_port > 0 ? make_listener(host, grpc_port) : -1;
    if (grpc_port > 0 && gfd < 0) return 1;
    epfd = epoll_create1(0);
    timer_fd = timerfd_create(CLOCK_MONOTONIC, TFD_NONBLOCK);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = lfd;
    epoll_ctl(epfd, EPOLL_CTL_ADD, lfd, &ev);
    if (gfd >= 0) {
      ev.data.fd = gfd;
      epoll_ctl(epfd, EPOLL_CTL_ADD, gfd, &ev);
    }
    ev.data.fd = timer_fd;
    epoll_ctl(epfd, EPOLL_CTL_ADD, timer_fd, &ev);
    fprintf(stderr, "seldon-edge listening on %s:%d grpc=%d (native=%d)\n",
            host ? host : "0.0.0.0", port, grpc_port, prog.native ? 1 : 0);

    std::vector<epoll_event> events(256);
    for (;;) {
      int n = epoll_wait(epfd, events.data(), (int)events.size(), -1);
      for (int i = 0; i < n; ++i) {
        int fd = events[i].data.fd;
        if (fd == lfd || fd == gfd) {
          for (;;) {
            int cfd = accept4(fd, nullptr, nullptr, SOCK_NONBLOCK);
            if (cfd < 0) break;
            int off = 1;
            setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &off, sizeof(off));
            Conn& c = conn(cfd);
            c.fd = cfd;
            c.in.clear();
            c.outbuf.clear();
            c.out_off = 0;
            c.want_close = false;
            c.waiting_ring = false;
            c.is_h2 = false;
            c.h2.reset();
            epoll_event cev{};
            cev.events = EPOLLIN;
            cev.data.fd = cfd;
            epoll_ctl(epfd, EPOLL_CTL_ADD, cfd, &cev);
          }
          continue;
        }
        if (fd == timer_fd) {
          uint64_t expirations;
          while (read(timer_fd, &expirations, 8) == 8) {
          }
          drain_ring_responses();
          continue;
        }
        Conn& c = conn(fd);
        if (c.fd != fd) continue;
        if (events[i].events & (EPOLLHUP | EPOLLERR)) {
          close_conn(c);
          continue;
        }
        if (events[i].events & EPOLLOUT) {
          epoll_event cev{};
          cev.events = EPOLLIN;
          cev.data.fd = fd;
          epoll_ctl(epfd, EPOLL_CTL_MOD, fd, &cev);
          flush_out(c);
          if (c.fd < 0) continue;
        }
        if (events[i].events & EPOLLIN) on_readable(c);
      }
    }
    return 0;
  }
};

}  // namespace

int main(int argc, char** argv) {
  const char* program_path = nullptr;
  const char* ring_base = nullptr;
  const char* openapi_path = nullptr;
  const char* host = nullptr;
  int port = 8000;
  int grpc_port = 0;
  int workers = 1;
  int ring_worker = 0;
  int max_inflight = 4096;
  for (int i = 1; i < argc; ++i) {
    std::string_view a = argv[i];
    auto next = [&]() -> const char* { return i + 1 < argc ? argv[++i] : nullptr; };
    if (a == "--program") program_path = next();
    else if (a == "--port") port = atoi(next());
    else if (a == "--grpc-port") grpc_port = atoi(next());
    else if (a == "--host") host = next();
    else if (a == "--ring") ring_base = next();
    else if (a == "--ring-worker") ring_worker = atoi(next());
    else if (a == "--openapi") openapi_path = next();
    else if (a == "--workers") workers = atoi(next());
    else if (a == "--max-inflight") max_inflight = atoi(next());
    else {
      fprintf(stderr, "unknown arg %s\n", argv[i]);
      return 2;
    }
  }
  if (!program_path) {
    fprintf(stderr,
            "usage: seldon_edge --program prog.json [--port N] [--host H] "
            "[--ring BASE] [--ring-worker W] [--openapi FILE] [--workers N]\n");
    return 2;
  }

  // SO_REUSEPORT worker processes (linear scaling on multi-core hosts);
  // parent and children all run an event loop on the shared port.
  for (int w = 1; w < workers; ++w) {
    pid_t pid = fork();
    if (pid == 0) break;  // child proceeds to serve
    if (pid < 0) return 1;
  }

  Server srv;
  srv.rng.seed();
  srv.init_grpc_constants();
  // --max-inflight 0 disables shedding entirely (unbounded parked work).
  srv.max_inflight =
      max_inflight > 0 ? (size_t)max_inflight : (size_t)-1;
  if (!load_program(program_path, srv.prog)) {
    fprintf(stderr, "cannot load program %s\n", program_path);
    return 1;
  }
  srv.metrics.deployment = srv.prog.deployment;
  srv.metrics.predictor = srv.prog.predictor;
  if (openapi_path) {
    FILE* f = fopen(openapi_path, "rb");
    if (f) {
      char tmp[8192];
      size_t n;
      while ((n = fread(tmp, 1, sizeof(tmp), f)) > 0) srv.openapi.append(tmp, n);
      fclose(f);
    }
  }
  if (ring_base) {
    std::string req = std::string(ring_base) + ".req";
    std::string resp = std::string(ring_base) + ".resp." + std::to_string(ring_worker);
    srv.req_ring = scr_attach(req.c_str());
    srv.resp_ring = scr_attach(resp.c_str());
    srv.ring_worker_id = (uint16_t)ring_worker;
    if (!srv.req_ring || !srv.resp_ring) {
      fprintf(stderr, "cannot attach rings at %s\n", ring_base);
      return 1;
    }
    srv.ring_slot = (uint32_t)scr_slot_size(srv.resp_ring);
  }
  return srv.run(host, port, grpc_port);
}
