// Shared-memory MPMC ring buffer for request/tensor staging.
//
// Role: the native data-plane piece of the TPU serving runtime. The reference
// delegates its native performance path to external C++ servers
// (integrations/tfserving, nvidia-inference-server — SURVEY.md §2 native-code
// note); here the native component is in-repo: transport worker processes
// (REST/gRPC frontends) stage decoded tensor payloads into a shared-memory
// ring, and the single device-owning engine process drains them in batches —
// no pickling, no socket hop, one memcpy each way.
//
// Design: Vyukov bounded MPMC queue. Each cell carries an atomic sequence
// number; producers claim cells with fetch_add on enqueue_pos, consumers with
// fetch_add on dequeue_pos. Lock-free, FIFO per producer, safe across
// processes (std::atomic<uint64_t> on x86-64/aarch64 over shared mmap).
//
// Layout in the mapped file (v2):
//   [Header][CellHeader 0..capacity-1 (64B each, contiguous)][slot 0..capacity-1]
// Cell headers are packed together rather than strided through the data
// region: creation then touches capacity*64B instead of one page per slot —
// on block storage where a fresh MAP_SHARED page fault costs ~10ms, the old
// strided layout took ~16s to create a 1GB ring (measured; see git history).
// Polling also scans a compact array instead of page-sized strides.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x53454c52494e4732ull;  // "SELRING2"

struct Header {
  std::atomic<uint64_t> magic;  // written last (release) so attachers see a
                                // fully initialised header (acquire)
  uint64_t capacity;   // power of two
  uint64_t slot_size;  // payload bytes per cell
  uint64_t slot_stride;  // slot_size rounded to 64B
  alignas(64) std::atomic<uint64_t> enqueue_pos;
  alignas(64) std::atomic<uint64_t> dequeue_pos;
};

struct alignas(64) CellHeader {  // one cache line per cell, packed array
  std::atomic<uint64_t> seq;
  uint32_t len;
};

struct Ring {
  Header* header;
  CellHeader* cells;  // contiguous array [capacity]
  uint8_t* slots;     // data region, slot_stride apart
  size_t map_len;
};

inline CellHeader* cell_at(const Ring* r, uint64_t idx) {
  return r->cells + (idx & (r->header->capacity - 1));
}

inline uint8_t* cell_data(const Ring* r, uint64_t idx) {
  return r->slots + (idx & (r->header->capacity - 1)) * r->header->slot_stride;
}

size_t total_size(uint64_t capacity, uint64_t slot_stride) {
  return sizeof(Header) + capacity * sizeof(CellHeader) + capacity * slot_stride;
}

}  // namespace

extern "C" {

// Create (or replace) a ring file. capacity must be a power of two.
// The ring is initialised in a temp file and atomically renamed over the
// target, so re-creating a ring never truncates the inode that still-attached
// workers have mapped (they keep the old ring; new attachers get the new one).
// Returns an opaque handle or nullptr.
void* scr_create(const char* path, uint64_t capacity, uint64_t slot_size) {
  if (capacity == 0 || (capacity & (capacity - 1)) != 0) return nullptr;
  uint64_t stride = (slot_size + 63) & ~63ull;  // 64B-align slots
  size_t len = total_size(capacity, stride);

  char tmp[4096];
  int n = ::snprintf(tmp, sizeof(tmp), "%s.tmp.%d", path, ::getpid());
  if (n < 0 || static_cast<size_t>(n) >= sizeof(tmp)) return nullptr;
  int fd = ::open(tmp, O_RDWR | O_CREAT | O_TRUNC, 0600);
  if (fd < 0) return nullptr;
  if (::ftruncate(fd, static_cast<off_t>(len)) != 0) {
    ::close(fd);
    ::unlink(tmp);
    return nullptr;
  }
  void* mem = ::mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) {
    ::unlink(tmp);
    return nullptr;
  }

  auto* h = static_cast<Header*>(mem);
  h->capacity = capacity;
  h->slot_size = slot_size;
  h->slot_stride = stride;
  h->enqueue_pos.store(0, std::memory_order_relaxed);
  h->dequeue_pos.store(0, std::memory_order_relaxed);

  auto* cells = reinterpret_cast<CellHeader*>(static_cast<uint8_t*>(mem) + sizeof(Header));
  auto* ring = new Ring{h, cells,
                        reinterpret_cast<uint8_t*>(cells + capacity), len};
  for (uint64_t i = 0; i < capacity; ++i) {
    cell_at(ring, i)->seq.store(i, std::memory_order_relaxed);
    cell_at(ring, i)->len = 0;
  }
  h->magic.store(kMagic, std::memory_order_release);
  if (::rename(tmp, path) != 0) {
    ::munmap(mem, len);
    ::unlink(tmp);
    delete ring;
    return nullptr;
  }
  return ring;
}

// Attach to an existing ring file. Returns nullptr on mismatch.
void* scr_attach(const char* path) {
  int fd = ::open(path, O_RDWR);
  if (fd < 0) return nullptr;
  struct stat st;
  if (::fstat(fd, &st) != 0 || st.st_size < static_cast<off_t>(sizeof(Header))) {
    ::close(fd);
    return nullptr;
  }
  void* mem = ::mmap(nullptr, static_cast<size_t>(st.st_size), PROT_READ | PROT_WRITE,
                     MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* h = static_cast<Header*>(mem);
  if (h->magic.load(std::memory_order_acquire) != kMagic ||
      static_cast<size_t>(st.st_size) < total_size(h->capacity, h->slot_stride)) {
    ::munmap(mem, static_cast<size_t>(st.st_size));
    return nullptr;
  }
  auto* cells = reinterpret_cast<CellHeader*>(static_cast<uint8_t*>(mem) + sizeof(Header));
  return new Ring{h, cells, reinterpret_cast<uint8_t*>(cells + h->capacity),
                  static_cast<size_t>(st.st_size)};
}

void scr_detach(void* handle) {
  auto* r = static_cast<Ring*>(handle);
  if (!r) return;
  ::munmap(r->header, r->map_len);
  delete r;
}

uint64_t scr_capacity(void* handle) { return static_cast<Ring*>(handle)->header->capacity; }
uint64_t scr_slot_size(void* handle) { return static_cast<Ring*>(handle)->header->slot_size; }

// Approximate occupancy (racy by nature; exact when quiescent).
uint64_t scr_size(void* handle) {
  auto* h = static_cast<Ring*>(handle)->header;
  uint64_t e = h->enqueue_pos.load(std::memory_order_acquire);
  uint64_t d = h->dequeue_pos.load(std::memory_order_acquire);
  return e > d ? e - d : 0;
}

// 0 = ok, -1 = full, -2 = payload too large.
int scr_push(void* handle, const void* data, uint32_t len) {
  auto* r = static_cast<Ring*>(handle);
  Header* h = r->header;
  if (len > h->slot_size) return -2;

  uint64_t pos = h->enqueue_pos.load(std::memory_order_relaxed);
  CellHeader* cell;
  for (;;) {
    cell = cell_at(r, pos);
    uint64_t seq = cell->seq.load(std::memory_order_acquire);
    intptr_t dif = static_cast<intptr_t>(seq) - static_cast<intptr_t>(pos);
    if (dif == 0) {
      if (h->enqueue_pos.compare_exchange_weak(pos, pos + 1, std::memory_order_relaxed))
        break;
    } else if (dif < 0) {
      return -1;  // full
    } else {
      pos = h->enqueue_pos.load(std::memory_order_relaxed);
    }
  }
  cell->len = len;
  std::memcpy(cell_data(r, pos), data, len);
  cell->seq.store(pos + 1, std::memory_order_release);
  return 0;
}

// Returns payload length (>=0) or -1 = empty, -3 = out buffer too small
// (item left in place).
int scr_pop(void* handle, void* out, uint32_t out_cap) {
  auto* r = static_cast<Ring*>(handle);
  Header* h = r->header;

  uint64_t pos = h->dequeue_pos.load(std::memory_order_relaxed);
  CellHeader* cell;
  for (;;) {
    cell = cell_at(r, pos);
    uint64_t seq = cell->seq.load(std::memory_order_acquire);
    intptr_t dif =
        static_cast<intptr_t>(seq) - static_cast<intptr_t>(pos + 1);
    if (dif == 0) {
      if (cell->len > out_cap) return -3;
      if (h->dequeue_pos.compare_exchange_weak(pos, pos + 1, std::memory_order_relaxed))
        break;
    } else if (dif < 0) {
      return -1;  // empty
    } else {
      pos = h->dequeue_pos.load(std::memory_order_relaxed);
    }
  }
  uint32_t len = cell->len;
  std::memcpy(out, cell_data(r, pos), len);
  cell->seq.store(pos + h->capacity, std::memory_order_release);
  return static_cast<int>(len);
}

// Batched drain: pops up to max_items payloads into out, packed as
// [u32 len][payload]... back to back. Returns the number of frames popped
// (0 when empty), or -3 when the ring is non-empty but the FIRST pending
// frame exceeds out_cap (matching scr_pop) — without the distinct code an
// undersized caller would spin forever on "0 popped" with no way to tell
// it from empty. *bytes_used receives the total packed size. Stops early
// when the next payload would not fit in out_cap (item left in place).
// One FFI round-trip replaces max_items ctypes calls on the Python side —
// at ~1.5us per ctypes crossing that is most of the per-frame drain cost
// at 20k+ rps.
int scr_pop_many(void* handle, void* out, uint32_t out_cap, uint32_t max_items,
                 uint32_t* bytes_used) {
  auto* r = static_cast<Ring*>(handle);
  Header* h = r->header;
  uint8_t* dst = static_cast<uint8_t*>(out);
  uint32_t off = 0;
  uint32_t count = 0;
  bool first_too_big = false;
  while (count < max_items) {
    uint64_t pos = h->dequeue_pos.load(std::memory_order_relaxed);
    CellHeader* cell;
    bool got = false;
    for (;;) {
      cell = cell_at(r, pos);
      uint64_t seq = cell->seq.load(std::memory_order_acquire);
      intptr_t dif = static_cast<intptr_t>(seq) - static_cast<intptr_t>(pos + 1);
      if (dif == 0) {
        if (off + 4 + cell->len > out_cap) {  // no room: leave in place
          if (count == 0) first_too_big = true;
          break;
        }
        if (h->dequeue_pos.compare_exchange_weak(pos, pos + 1,
                                                 std::memory_order_relaxed)) {
          got = true;
          break;
        }
      } else if (dif < 0) {
        break;  // empty
      } else {
        pos = h->dequeue_pos.load(std::memory_order_relaxed);
      }
    }
    if (!got) break;
    uint32_t len = cell->len;
    std::memcpy(dst + off, &len, 4);
    std::memcpy(dst + off + 4, cell_data(r, pos), len);
    cell->seq.store(pos + r->header->capacity, std::memory_order_release);
    off += 4 + len;
    ++count;
  }
  if (bytes_used) *bytes_used = off;
  if (count == 0 && first_too_big) return -3;
  return static_cast<int>(count);
}

// Model-executor response fast path: builds and pushes n kind-2 OK
// responses straight into ring slots — zero intermediate buffers, one FFI
// crossing for a whole micro-batch chunk. Frame layout must mirror
// ModelExecutor._ok_response (transport/ipc.py):
//   [u32 req_id][u8 status=0][u8 dtype_code][u8 ndim]
//   [u32 dims x ndim][u32 frag_len][frag][rows * row_nvals f8]
// data holds stacked result rows; response i takes row_counts[i] rows
// starting at row_offsets[i]; dims = (row_counts[i], tail_dims...). All
// responses share the fragment (static-fragment chunks only; dynamic-tag
// components never take this path).
// Returns count actually pushed (< n when the ring filled; caller retries
// the tail) or -2 when a response exceeds slot_size.
int scr_push_model_resps(void* handle, const uint32_t* req_ids,
                         const uint64_t* row_offsets, const uint32_t* row_counts,
                         uint32_t n, const double* data, uint64_t row_nvals,
                         const uint32_t* tail_dims, uint32_t n_tail,
                         const char* frag, uint32_t frag_len, uint32_t dtype_code) {
  auto* r = static_cast<Ring*>(handle);
  Header* h = r->header;
  for (uint32_t i = 0; i < n; ++i) {
    uint32_t ndim = 1 + n_tail;
    uint64_t payload = static_cast<uint64_t>(row_counts[i]) * row_nvals * 8;
    uint64_t total = 4 + 1 + 1 + 1 + 4ull * ndim + 4 + frag_len + payload;
    if (total > h->slot_size) return -2;

    uint64_t pos = h->enqueue_pos.load(std::memory_order_relaxed);
    CellHeader* cell;
    for (;;) {
      cell = cell_at(r, pos);
      uint64_t seq = cell->seq.load(std::memory_order_acquire);
      intptr_t dif = static_cast<intptr_t>(seq) - static_cast<intptr_t>(pos);
      if (dif == 0) {
        if (h->enqueue_pos.compare_exchange_weak(pos, pos + 1,
                                                 std::memory_order_relaxed))
          break;
      } else if (dif < 0) {
        return static_cast<int>(i);  // full: caller retries the tail
      } else {
        pos = h->enqueue_pos.load(std::memory_order_relaxed);
      }
    }
    uint8_t* dst = cell_data(r, pos);
    std::memcpy(dst, &req_ids[i], 4);
    dst[4] = 0;  // status ok
    dst[5] = static_cast<uint8_t>(dtype_code);  // MATH dtype (0=f32, 1=f64):
    // payload bytes are always f8, but combiner averaging parity tracks the
    // model's original output dtype (edge.cc resolve_dval promotion)
    dst[6] = static_cast<uint8_t>(ndim);
    uint32_t off = 7;
    std::memcpy(dst + off, &row_counts[i], 4);
    off += 4;
    for (uint32_t d = 0; d < n_tail; ++d) {
      std::memcpy(dst + off, &tail_dims[d], 4);
      off += 4;
    }
    std::memcpy(dst + off, &frag_len, 4);
    off += 4;
    if (frag_len) std::memcpy(dst + off, frag, frag_len);
    off += frag_len;
    std::memcpy(dst + off, data + row_offsets[i] * row_nvals, payload);
    cell->len = static_cast<uint32_t>(off + payload);
    cell->seq.store(pos + 1, std::memory_order_release);
  }
  return static_cast<int>(n);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Test hooks for the seeded-router RNG replays (native/np_rng.h): pytest
// compares these draw-for-draw against numpy / CPython so the native edge's
// seeded routing is PROVEN bit-exact, not assumed.
// ---------------------------------------------------------------------------
#include "np_rng.h"

extern "C" {

void* np_rng_new(uint64_t seed) { return new nprng::NpRng(seed); }
void np_rng_free(void* h) { delete static_cast<nprng::NpRng*>(h); }
double np_rng_random(void* h) { return static_cast<nprng::NpRng*>(h)->random(); }
uint64_t np_rng_next64(void* h) { return static_cast<nprng::NpRng*>(h)->next64(); }
uint64_t np_rng_integers(void* h, uint64_t n) {
  return static_cast<nprng::NpRng*>(h)->integers(n);
}
double np_rng_standard_normal(void* h) {
  return static_cast<nprng::NpRng*>(h)->standard_normal();
}
double np_rng_standard_exponential(void* h) {
  return static_cast<nprng::NpRng*>(h)->standard_exponential();
}
double np_rng_standard_gamma(void* h, double shape) {
  return static_cast<nprng::NpRng*>(h)->standard_gamma(shape);
}
double np_rng_beta(void* h, double a, double b) {
  return static_cast<nprng::NpRng*>(h)->beta(a, b);
}

void* py_rng_new(uint64_t seed) { return new nprng::PyRng(seed); }
void py_rng_free(void* h) { delete static_cast<nprng::PyRng*>(h); }
double py_rng_random(void* h) { return static_cast<nprng::PyRng*>(h)->random(); }
uint64_t py_rng_randrange(void* h, uint64_t n) {
  return static_cast<nprng::PyRng*>(h)->randrange(n);
}

}  // extern "C"
