"""Async load generator for REST and gRPC serving endpoints.

Capability of the reference's distributed locust drivers
(`util/loadtester/scripts/predict_rest_locust.py:17-80`,
`predict_grpc_locust.py`): N concurrent clients fire predict requests
(optionally contract-fuzzed payloads), collect latencies, and report
throughput + percentiles. One process with asyncio concurrency replaces the
locust master/slave pair for single-host runs; scale out by running multiple
processes (the helm chart's slave count).

Used by benchmarks and the `loadtest` CLI subcommand; prints a single JSON
report compatible with BENCH tooling.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Any, Callable, Dict, Optional

import numpy as np


def percentile_stats(latencies_s) -> Dict[str, float]:
    lat = np.asarray(sorted(latencies_s))
    if lat.size == 0:
        return {}
    pct = lambda p: float(np.percentile(lat, p) * 1000.0)  # noqa: E731
    return {
        "p50_ms": round(pct(50), 3),
        "p90_ms": round(pct(90), 3),
        "p95_ms": round(pct(95), 3),
        "p99_ms": round(pct(99), 3),
        "mean_ms": round(float(lat.mean() * 1000.0), 3),
        "max_ms": round(float(lat.max() * 1000.0), 3),
    }


async def run_rest_load(
    url: str,
    payload_fn: Callable[[], Dict[str, Any]],
    clients: int = 16,
    duration_s: float = 10.0,
    warmup_s: float = 1.0,
) -> Dict[str, Any]:
    """Closed-loop: each client fires its next request when the previous one
    answers (the locust model)."""
    import aiohttp

    latencies: list = []
    errors = [0]
    stop_at = [0.0]

    async def client(session):
        while time.perf_counter() < stop_at[0]:
            t0 = time.perf_counter()
            try:
                async with session.post(url, json=payload_fn()) as resp:
                    await resp.read()
                    ok = resp.status == 200
            except Exception:
                ok = False
            dt = time.perf_counter() - t0
            if ok:
                latencies.append((t0, dt))
            else:
                errors[0] += 1

    conn = aiohttp.TCPConnector(limit=clients * 2)
    async with aiohttp.ClientSession(connector=conn) as session:
        # warmup (excluded from stats)
        stop_at[0] = time.perf_counter() + warmup_s
        await asyncio.gather(*[client(session) for _ in range(min(4, clients))])
        latencies.clear()
        errors[0] = 0
        start = time.perf_counter()
        stop_at[0] = start + duration_s
        await asyncio.gather(*[client(session) for _ in range(clients)])
        elapsed = time.perf_counter() - start

    lat_only = [d for (_, d) in latencies]
    return {
        "transport": "rest",
        "clients": clients,
        "duration_s": round(elapsed, 3),
        "requests": len(lat_only),
        "errors": errors[0],
        "rps": round(len(lat_only) / elapsed, 2) if elapsed > 0 else 0.0,
        **percentile_stats(lat_only),
    }


def run_grpc_load(
    target: str,
    payload_fn: Callable[[], Any],
    clients: int = 8,
    duration_s: float = 10.0,
    warmup_s: float = 1.0,
    service: str = "Seldon",
) -> Dict[str, Any]:
    """Thread-based closed loop over blocking gRPC stubs."""
    import threading

    from seldon_core_tpu.transport import grpc_client

    latencies: list = []
    errors = [0]
    lock = threading.Lock()
    stop_at = [time.perf_counter() + warmup_s]

    def worker(collect: bool):
        while time.perf_counter() < stop_at[0]:
            t0 = time.perf_counter()
            try:
                grpc_client.call_sync(target, "Predict", payload_fn(), service=service)
                ok = True
            except Exception:
                ok = False
            dt = time.perf_counter() - t0
            with lock:
                if not collect:
                    continue
                if ok:
                    latencies.append(dt)
                else:
                    errors[0] += 1

    warm = [threading.Thread(target=worker, args=(False,)) for _ in range(min(4, clients))]
    for t in warm:
        t.start()
    for t in warm:
        t.join()

    start = time.perf_counter()
    stop_at[0] = start + duration_s
    threads = [threading.Thread(target=worker, args=(True,)) for _ in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - start

    return {
        "transport": "grpc",
        "clients": clients,
        "duration_s": round(elapsed, 3),
        "requests": len(latencies),
        "errors": errors[0],
        "rps": round(len(latencies) / elapsed, 2) if elapsed > 0 else 0.0,
        **percentile_stats(latencies),
    }


def default_payload_fn(contract_path: Optional[str] = None, batch: int = 1):
    """Random contract-conforming payloads, or a fixed 1x2 tensor."""
    if contract_path:
        from seldon_core_tpu.client.contract import generate_batch, load_contract

        contract = load_contract(contract_path)

        def fn():
            arr = generate_batch(contract, batch)
            return {"data": {"ndarray": arr.tolist()}}

        return fn
    fixed = {"data": {"tensor": {"shape": [batch, 2], "values": [1.0, 2.0] * batch}}}
    return lambda: fixed


def main(args) -> None:
    payload_fn = default_payload_fn(args.contract, args.batch)
    if args.grpc:
        from seldon_core_tpu.contracts.payload import SeldonMessage

        json_fn = payload_fn
        msg_fn = lambda: SeldonMessage.from_dict(json_fn())  # noqa: E731
        report = run_grpc_load(
            f"{args.host}:{args.port}", msg_fn, clients=args.clients, duration_s=args.duration
        )
    else:
        url = f"http://{args.host}:{args.port}/api/v0.1/predictions"
        report = asyncio.run(
            run_rest_load(url, payload_fn, clients=args.clients, duration_s=args.duration)
        )
    print(json.dumps(report))
