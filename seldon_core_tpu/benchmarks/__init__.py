"""Load-generation harness (capability of the reference's locust-based
`util/loadtester/` + loadtesting helm chart)."""
