"""Distributed load-generation fleet.

The reference load-tests with a locust master + slave fleet spread over
nodes (`util/loadtester/scripts/predict_rest_locust.py:17-53`,
`helm-charts/seldon-core-loadtesting/templates/{locust-master,locust-slave}
.yaml`). The equivalent here drives the native closed-loop generators
(native/loadgen_http.cc, loadgen_grpc.cc) as a fleet:

- **local fleet**: N generator processes on this host, one per core,
  started concurrently against the same target (a single process saturates
  ~1 core; the fleet scales the offered load linearly);
- **remote workers**: ``loadtest-worker --listen <port>`` turns any host
  into a slave — the master connects over TCP, ships the job spec as one
  JSON object, and collects the report (the locust master/slave wire role,
  minus the UI).

Reports merge by summing throughput/requests/failures; merged latency
percentiles are request-count-weighted averages of the per-worker
percentiles (approximate — workers report quantiles, not histograms — and
labelled as such in the report).
"""

from __future__ import annotations

import json
import socket
import subprocess
import threading
from typing import Any, Dict, List, Optional


def _loadgen_binary(grpc: bool) -> str:
    from seldon_core_tpu.runtime.edgeprogram import LOADGEN_BINARY, build_edge_binaries

    if not build_edge_binaries():
        raise RuntimeError("native loadgen unavailable (no C++ toolchain)")
    return LOADGEN_BINARY + ("_grpc" if grpc else "")


def run_one(job: Dict[str, Any]) -> Dict[str, Any]:
    """Run one native generator to completion; returns its JSON report."""
    grpc = bool(job.get("grpc"))
    args = [
        _loadgen_binary(grpc),
        "--host", str(job.get("host", "127.0.0.1")),
        "--port", str(job["port"]),
        "--connections", str(job.get("connections", 32)),
        "--duration", str(job.get("duration", 10.0)),
        "--warmup", str(job.get("warmup", 1.0)),
        "--label", str(job.get("label", "fleet")),
    ]
    if not grpc:
        if job.get("body"):
            args += ["--body", job["body"]]
        if job.get("path"):
            args += ["--path", job["path"]]
    out = subprocess.run(args, capture_output=True, text=True, check=False)
    if out.returncode not in (0, 3):
        raise RuntimeError(f"loadgen failed rc={out.returncode}: {out.stderr[:400]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def merge_reports(reports: List[Dict[str, Any]]) -> Dict[str, Any]:
    reports = [r for r in reports if r]
    if not reports:
        raise ValueError("no worker reports to merge")
    total_requests = sum(r.get("requests", 0) for r in reports)
    merged_lat: Dict[str, float] = {}
    keys = reports[0].get("latency_ms", {}).keys()
    for key in keys:
        if key == "max":
            merged_lat[key] = max(r["latency_ms"][key] for r in reports)
        else:
            weights = [max(r.get("requests", 0), 1) for r in reports]
            merged_lat[key] = round(
                sum(r["latency_ms"][key] * w for r, w in zip(reports, weights))
                / sum(weights),
                3,
            )
    return {
        "workers": len(reports),
        "throughput_rps": round(sum(r.get("throughput_rps", 0.0) for r in reports), 2),
        "requests": total_requests,
        "failures": sum(r.get("failures", 0) for r in reports),
        "duration_s": max(r.get("duration_s", 0.0) for r in reports),
        "connections": sum(r.get("connections", 0) for r in reports),
        "latency_ms": merged_lat,
        "latency_note": "percentiles are request-weighted averages of per-worker quantiles",
        "per_worker": reports,
    }


def run_local_fleet(
    job: Dict[str, Any],
    n_workers: int,
    per_worker: Optional[List[Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    """N concurrent generator processes on this host, merged report.
    ``per_worker[i]`` overrides job fields for worker i (e.g. a distinct
    contract-generated body per worker)."""
    reports: List[Optional[Dict[str, Any]]] = [None] * n_workers
    errors: List[Exception] = []

    def work(i: int) -> None:
        w_job = dict(job, label=f"{job.get('label', 'fleet')}-w{i}")
        if per_worker and i < len(per_worker):
            w_job.update(per_worker[i])
        try:
            reports[i] = run_one(w_job)
        except Exception as e:  # surfaced after join
            errors.append(e)

    threads = [threading.Thread(target=work, args=(i,)) for i in range(n_workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
    return merge_reports([r for r in reports if r])


# ---------------------------------------------------------------- workers
def worker_serve(listen_port: int, host: str = "127.0.0.1", once: bool = False,
                 token: Optional[str] = None) -> None:
    """Slave loop: accept a connection, read one JSON job (newline-framed),
    run it, write the JSON report back. One job at a time — load generation
    wants the whole host.

    Binds loopback by default; a worker exposed beyond localhost would let
    any TCP peer direct sustained load at an arbitrary host:port, so
    non-loopback binds require ``token`` and reject jobs whose envelope
    doesn't carry the matching ``token`` field."""
    if token is None and host not in ("127.0.0.1", "localhost", "::1"):
        raise ValueError(
            f"refusing to bind {host} without --token: an open worker is a "
            "traffic-amplification vector")
    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((host, listen_port))
    srv.listen(4)
    print(f"loadtest worker listening on {host}:{srv.getsockname()[1]}", flush=True)
    while True:
        conn, _ = srv.accept()
        served = False
        try:
            # a held-open probe connection must not wedge the worker
            conn.settimeout(30.0)
            f = conn.makefile("rwb")
            line = f.readline()
            if line:
                job = json.loads(line)
                if token is not None and job.get("token") != token:
                    f.write(json.dumps({"error": "bad token"}).encode() + b"\n")
                    f.flush()
                    continue
                job.pop("token", None)
                try:
                    conn.settimeout(float(job.get("duration", 10.0)) + 60.0)
                    report = run_one(job)
                    served = True
                except Exception as e:
                    report = {"error": str(e)}
                    served = True
                f.write(json.dumps(report).encode() + b"\n")
                f.flush()
        except (socket.timeout, OSError, ValueError):
            pass  # bad/slow client; keep serving
        finally:
            conn.close()
        # --once exits only after a real job, not after a probe connect
        if once and served:
            srv.close()
            return


def run_distributed(workers: List[str], job: Dict[str, Any],
                    timeout_s: Optional[float] = None,
                    per_worker: Optional[List[Dict[str, Any]]] = None,
                    token: Optional[str] = None) -> Dict[str, Any]:
    """Master: ship the job to every worker (host:port), merge the reports."""
    if token is not None:
        job = dict(job, token=token)
    if timeout_s is None:
        timeout_s = float(job.get("duration", 10.0)) + float(job.get("warmup", 1.0)) + 30.0
    reports: List[Optional[Dict[str, Any]]] = [None] * len(workers)
    errors: List[Exception] = []

    def drive(i: int, addr: str) -> None:
        host, _, port = addr.rpartition(":")
        try:
            with socket.create_connection((host or "127.0.0.1", int(port)),
                                          timeout=timeout_s) as conn:
                conn.settimeout(timeout_s)
                f = conn.makefile("rwb")
                w_job = dict(job, label=f"{job.get('label', 'fleet')}-{addr}")
                if per_worker and i < len(per_worker):
                    w_job.update(per_worker[i])
                f.write(json.dumps(w_job).encode() + b"\n")
                f.flush()
                resp = json.loads(f.readline())
            if "error" in resp:
                raise RuntimeError(f"worker {addr}: {resp['error']}")
            reports[i] = resp
        except Exception as e:
            errors.append(e)

    threads = [threading.Thread(target=drive, args=(i, w)) for i, w in enumerate(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
    return merge_reports([r for r in reports if r])
