"""Pallas-fused ResNet identity-residual chains for TPU serving.

Why this kernel exists: the single-chip ResNet-50 serving profile
(`benchmarks/profile_summary.json`) attributes ~79% of leaf device time to
*elementwise* fusion clusters rooted at residual-add/relu over the 56x56
activations — XLA on this backend leaves each relu / residual-add as its own
HBM round trip instead of folding it into the conv epilogues. An identity
bottleneck block (1x1 -> relu -> 3x3 -> relu -> 1x1 -> +residual -> relu)
over a (56, 56, 256) activation streams the ~1.6 MB/image input tensor many
times in that regime. This kernel computes the ENTIRE block — and optionally
a chain of consecutive identity blocks — per batch image inside VMEM: one
HBM read of x, one HBM write of the result, weights resident.

Shapes follow the folded-BN inference model (`models/resnet.py`,
``fold_batchnorm``): convs carry biases, BN is gone. Only *identity* blocks
(residual.shape == output.shape, stride 1) qualify; the strided/projection
block that opens each stage stays on XLA.

The 3x3 conv is expressed MXU-natively as 9 shifted (H*W, F) @ (F, F)
matmuls over the flattened spatial dim. Vertical out-of-range taps land in
an explicit zero-pad region of the flattened buffer; horizontal wraps (row
h, col 55 shifted +1 would alias row h+1, col 0) are killed by a per-shift
column mask — bit-equivalent to SAME zero padding.

Reference parity target: torch/CUDA frameworks hand-fuse these chains the
same way (reference seldon-core has no kernel tier at all — its model
runtimes inherit cuDNN fusion); here the fusion is explicit because the
measured XLA schedule leaves the bandwidth on the table.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _block_param_list(blocks: Sequence[dict]) -> list:
    """Flatten per-block folded params into the kernel's operand order.

    Each block contributes (w1, b1, w2, b2, w3, b3) with shapes
    w1 (C, F), b1 (F,), w2 (3, 3, F, F), b2 (F,), w3 (F, C), b3 (C,).
    w2 is flattened to (9F, F) — the im2col operand, tap-major to match the
    kernel's tap concatenation order; biases to (1, n) for 2D layout.
    """
    out = []
    for blk in blocks:
        f = blk["w1"].shape[1]
        c = blk["w1"].shape[0]
        if blk["w2"].shape[:2] != (3, 3):
            raise ValueError(f"3x3 conv expected, got {blk['w2'].shape}")
        out.extend(
            [
                blk["w1"],
                blk["b1"].reshape(1, f),
                blk["w2"].reshape(9 * f, f),
                blk["b2"].reshape(1, f),
                blk["w3"],
                blk["b3"].reshape(1, c),
            ]
        )
    return out


def _chunking(hw: int) -> tuple:
    """(n_chunks, rows-per-chunk) for the in-kernel matmul row chunking."""
    n_chunks = max(1, hw // 1024)
    while hw % n_chunks:
        n_chunks -= 1
    return n_chunks, hw // n_chunks


def _chain_kernel(h: int, w: int, n_blocks: int, *refs):
    """One grid program = `group` batch images through `n_blocks` identity
    blocks. The images are stacked along the flattened row axis; per-shift
    row/col masks stop 3x3 taps from bleeding across image seams or
    wrapping around row ends (bit-equivalent to SAME zero padding).

    refs layout: x_ref, (w1, b1, w2, b2, w3, b3) * n_blocks, out_ref,
    im2col scratch (rows, 9F). x_ref/out_ref block shape:
    (1, group*H*W, C); h/w are PER-IMAGE dims.
    """
    x_ref = refs[0]
    out_ref = refs[-2]
    im2col_ref = refs[-1]

    x = x_ref[0]  # (group*HW, C) bf16
    hw = x.shape[0]
    dtype = x.dtype

    # Validity masks per tap offset: the tap for OUTPUT position (row, col)
    # reads flat index + dh*w + dw, which aliases a wrong row (horizontal
    # wrap) or a neighboring image (vertical seam) unless row+dh and col+dw
    # are in-bounds for THIS image. With one image per program (hw == h*w)
    # vertical out-of-range taps land in the explicit zero padding, so row
    # masks are only needed for multi-image seams.
    flat = jax.lax.broadcasted_iota(jnp.int32, (hw, 1), 0)
    col = flat % w
    col_ok = {-1: col >= 1, 0: None, 1: col <= w - 2}
    if hw == h * w:
        row_ok = {-1: None, 0: None, 1: None}
    else:
        row = (flat // w) % h
        row_ok = {-1: row >= 1, 0: None, 1: row <= h - 2}

    def tap_mask(dh, dw):
        ok = None
        for part in (row_ok[dh], col_ok[dw]):
            if part is not None:
                ok = part if ok is None else jnp.logical_and(ok, part)
        return None if ok is None else ok.astype(dtype)

    # Row-chunked matmuls: a full (HW, C) f32 intermediate is 3.2 MB at
    # 3136x256 and the un-chunked kernel blows the 16 MB scoped-VMEM stack
    # (measured: 19.02M). Chunking the 1x1 dots and casting to bf16 eagerly
    # keeps live f32 transients to one chunk.
    n_chunks, rows = _chunking(hw)

    def chunked_matmul_bf16(a, w_ref, b_ref, relu, extra=None):
        """relu(a @ w + b [+ extra]) computed per row-chunk, bf16 out."""
        outs = []
        for ci in range(n_chunks):
            part = jnp.dot(
                a[ci * rows:(ci + 1) * rows, :], w_ref[:],
                preferred_element_type=jnp.float32,
            )
            part = (part + b_ref[:]).astype(dtype)
            if extra is not None:
                part = part + extra[ci * rows:(ci + 1) * rows, :]
            if relu:
                part = jnp.maximum(part, 0.0)
            outs.append(part)
        return outs[0] if n_chunks == 1 else jnp.concatenate(outs, axis=0)

    for i in range(n_blocks):
        w1_ref, b1_ref, w2_ref, b2_ref, w3_ref, b3_ref = refs[1 + 6 * i : 7 + 6 * i]

        # --- 1x1 reduce: (HW, C) @ (C, F) -> relu -> bf16
        y1 = chunked_matmul_bf16(x, w1_ref, b1_ref, relu=True)  # (HW, F)

        # --- 3x3 conv in im2col form, row-chunked: the 9 taps concatenate
        # along lanes into (rows, 9F) and ONE (rows, 9F) @ (9F, F) matmul
        # replaces 9 skinny K=F matmuls — at F=64 the skinny form fills only
        # a quarter of the 128x128 MXU (K=64, N=64) while im2col's K=9F
        # streams full K tiles (measured: the 9-tap form lost 23% vs XLA on
        # the 56x56 chain; see benchmarks/MFU_NOTES.md round-5 log). The
        # kernel operand is reshaped to (9F, F) outside the kernel. Zero
        # rows above/below keep the shifted slices in bounds; the masks
        # above supply the actual SAME-padding semantics.
        f = y1.shape[1]
        y1p = jnp.concatenate(
            [jnp.zeros((w + 1, f), dtype), y1, jnp.zeros((w + 1, f), dtype)], axis=0
        )
        w2flat = w2_ref[:]  # (9F, F), pre-flattened tap-major
        y2_parts = []
        for ci in range(n_chunks):
            # Stage taps through the im2col scratch ref: a vector concat of
            # differently-shifted slices is unsupported (Mosaic: "offset
            # mismatch on non-concat dimension"); stores normalize layout.
            for dh in (-1, 0, 1):
                for dw in (-1, 0, 1):
                    shift = dh * w + dw
                    lo = w + 1 + shift + ci * rows  # static: lowers as
                    tap = y1p[lo:lo + rows, :]  # lax.slice (dynamic_slice
                    # has no Pallas TPU lowering)
                    m = tap_mask(dh, dw)
                    if m is not None:
                        tap = tap * m[ci * rows:(ci + 1) * rows, :]
                    k = 3 * (dh + 1) + (dw + 1)
                    im2col_ref[:, k * f:(k + 1) * f] = tap
            acc = jnp.dot(
                im2col_ref[:], w2flat,
                preferred_element_type=jnp.float32,
            )
            y2_parts.append(
                jnp.maximum(acc + b2_ref[:], 0.0).astype(dtype)
            )
        y2 = y2_parts[0] if n_chunks == 1 else jnp.concatenate(y2_parts, axis=0)

        # --- 1x1 expand + residual + relu (residual add in bf16, matching
        # the folded flax graph's dtype chain)
        x = chunked_matmul_bf16(y2, w3_ref, b3_ref, relu=False, extra=x)
        x = jnp.maximum(x, 0.0)

    out_ref[0] = x


def fused_identity_chain(
    x: jax.Array,
    blocks: Sequence[dict],
    *,
    group: int = 1,
    interpret: bool = False,
) -> jax.Array:
    """Run consecutive folded-BN identity bottleneck blocks as ONE Pallas
    kernel: per batch image, one HBM read of x and one HBM write of the
    final activation; every intermediate lives in VMEM.

    x: (B, H, W, C) activations (bf16 recommended).
    blocks: per-block folded params, dicts with w1 (C,F), b1, w2 (3,3,F,F),
        b2, w3 (F,C), b3 — see fold_batchnorm (models/resnet.py).
    group: batch images per grid program (raise for small spatial dims so
        the matmul M stays MXU-sized; B % group must be 0).
    """
    b, h, w, c = x.shape
    if b % group:
        raise ValueError(f"batch {b} not divisible by group {group}")
    params = _block_param_list(blocks)
    n_blocks = len(blocks)

    x2d = x.reshape(b // group, group * h * w, c)
    grid = (b // group,)
    data_spec = pl.BlockSpec(
        (1, group * h * w, c), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
    )
    w_specs = [pl.BlockSpec(memory_space=pltpu.VMEM) for _ in params]

    # Cost estimate: per image per block, 2*HW*C*F (x2) + 2*HW*9*F*F flops;
    # bytes ~= one read + one write of (HW, C) per chain end-to-end.
    f = blocks[0]["w1"].shape[1]
    flops = 2 * b * h * w * (2 * c * f + 9 * f * f) * n_blocks
    bytes_accessed = 2 * b * h * w * c * x.dtype.itemsize

    # Multi-block chains keep each block's transients live on the Mosaic
    # stack (measured: ~8M/block at 56x56x256, vs the 16M default scoped
    # limit); the chip accepts far larger scoped VMEM (the r4 flag sweep ran
    # XLA at a 128 MiB scoped limit), so raise the cap with the chain depth.
    compiler_params = None
    if not interpret and n_blocks > 1:
        compiler_params = pltpu.CompilerParams(
            vmem_limit_bytes=min(128, 16 + 10 * n_blocks) * 1024 * 1024
        )
    _, chunk_rows = _chunking(group * h * w)
    out = pl.pallas_call(
        partial(_chain_kernel, h, w, n_blocks),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, x.dtype),
        grid=grid,
        in_specs=[data_spec] + w_specs,
        out_specs=data_spec,
        scratch_shapes=[pltpu.VMEM((chunk_rows, 9 * f), x.dtype)],
        cost_estimate=pl.CostEstimate(
            flops=flops, bytes_accessed=bytes_accessed, transcendentals=0
        ),
        compiler_params=compiler_params,
        interpret=interpret,
    )(x2d, *params)
    return out.reshape(b, h, w, c)


def identity_chain_ref(x: jax.Array, blocks: Sequence[dict]) -> jax.Array:
    """Pure-XLA reference for the fused chain (same numerics contract:
    f32 matmul accumulation, bf16 handoffs, SAME-padded 3x3)."""
    dtype = x.dtype
    for blk in blocks:
        y = jnp.maximum(
            jnp.einsum("bhwc,cf->bhwf", x, blk["w1"],
                       preferred_element_type=jnp.float32)
            + blk["b1"],
            0.0,
        ).astype(dtype)
        y = jnp.maximum(
            jax.lax.conv_general_dilated(
                y.astype(dtype),
                blk["w2"].astype(dtype),
                (1, 1),
                ((1, 1), (1, 1)),
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                preferred_element_type=jnp.float32,
            )
            + blk["b2"],
            0.0,
        ).astype(dtype)
        y = (
            jnp.einsum("bhwf,fc->bhwc", y, blk["w3"],
                       preferred_element_type=jnp.float32)
            + blk["b3"]
        ).astype(dtype)
        x = jnp.maximum(x + y, 0.0)
    return x


def _is_identity_block(scope: dict) -> bool:
    return "conv_proj" not in scope


def folded_block_params(scope: dict) -> dict:
    """Map one folded BottleneckBlock_* param scope to the kernel's dict."""
    return {
        "w1": scope["Conv_0"]["kernel"].reshape(
            scope["Conv_0"]["kernel"].shape[-2:]
        ),
        "b1": scope["Conv_0"]["bias"],
        "w2": scope["Conv_1"]["kernel"],
        "b2": scope["Conv_1"]["bias"],
        "w3": scope["Conv_2"]["kernel"].reshape(
            scope["Conv_2"]["kernel"].shape[-2:]
        ),
        "b3": scope["Conv_2"]["bias"],
    }


__all__ = [
    "fused_identity_chain",
    "identity_chain_ref",
    "folded_block_params",
]
