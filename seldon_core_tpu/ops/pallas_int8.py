"""Pallas TPU kernel: int8 weight-only matmul with in-kernel dequantization.

The serving path for weight-only int8 (ops/quantize.py) relies on XLA to
fuse the convert+multiply dequant into the consuming matmul. This kernel is
the explicit-control variant of that contract — the weight tile crosses
HBM->VMEM as int8 (half the bytes of bf16), is dequantized in VMEM
registers, and feeds the MXU per (M, N) grid tile with f32 accumulation —
the quantization-kernel pattern from the TPU Pallas playbook. Its role: an
explicit-control experiment (``int8_dense`` / ``int8_matmul``) for
validating/benching the XLA fusion path against a known-good explicit
schedule. The public serving entry point (``ops.quantize.quantized_matmul``)
uses the fused XLA expression — the round-4 TPU decision bench measured
this kernel at 0.55-0.79x XLA on the decode GEMM shapes, so swapping it
into the model families stays gated on a benchmark win that hasn't
materialised.

``int8_matmul`` pads all dims to MXU-friendly tiles, runs the kernel on
TPU, and falls back to the equivalent XLA expression elsewhere (tests run
the kernel itself via the Pallas interpreter, so the body is exercised on
CPU).
"""

from __future__ import annotations

import functools

import numpy as np


def _kernel(x_ref, q_ref, s_ref, o_ref):
    import jax.numpy as jnp

    # dequant in VMEM: int8 tile -> f32, scaled per output channel
    w = q_ref[...].astype(jnp.float32) * s_ref[...].astype(jnp.float32)[None, :]
    o_ref[...] = jnp.dot(
        x_ref[...].astype(jnp.float32), w, preferred_element_type=jnp.float32
    ).astype(o_ref.dtype)


@functools.lru_cache(maxsize=None)
def _tile_sizes(m: int, n: int):
    # lane dim is fixed at 128; sublane tile shrinks for small batches but
    # stays a multiple of the f32 min tile (8)
    tm = 128 if m >= 128 else max(8, 1 << max(m - 1, 0).bit_length())
    return tm, 128


_TPU_COMPILE_STATUS: str | None = None


def probe_tpu_compile(force: bool = False) -> str:
    """Attempt one tiny int8_matmul Pallas compile+run on the TPU backend
    and cache the outcome for this process ("ok" or "error: ...").

    Backend support has flapped across rounds (rejected everything in round
    3, accepted in round 4 — benchmarks/MFU_NOTES.md measurement log);
    rather than letting either state go stale, ``int8_matmul`` re-verifies
    it here on first TPU use each process and falls back to the XLA-fused
    dequant expression when the kernel can't compile, so the explicit
    kernel entry points (int8_matmul / int8_dense) never surface a backend
    compile error. The *serving* path (ops.quantize.quantized_matmul) uses
    the XLA expression unconditionally — a measured decision, not a
    compile fallback (round-4 bench: the kernel is 0.55-0.79x XLA on the
    decode GEMM shapes)."""
    global _TPU_COMPILE_STATUS
    if _TPU_COMPILE_STATUS is not None and not force:
        return _TPU_COMPILE_STATUS
    import jax
    import jax.numpy as jnp

    # shardlint: allow-mesh-rederivation(Pallas backend probe: asks which platform compiles, no mesh/device-world is derived)
    if jax.devices()[0].platform != "tpu":
        _TPU_COMPILE_STATUS = "error: no TPU backend in this process"
        return _TPU_COMPILE_STATUS
    try:
        x = jnp.zeros((8, 128), jnp.bfloat16)
        q = jnp.zeros((128, 128), jnp.int8)
        s = jnp.ones((128,), jnp.float32)
        # graftlint: allow-host-sync-in-hot-path(one-time startup probe: the sync is the point — prove the kernel compiles AND runs before enabling the compiled path)
        np.asarray(int8_matmul(x, q, s, interpret=False, _probe=True))
        _TPU_COMPILE_STATUS = "ok"
    except Exception as e:  # noqa: BLE001 — any compile/runtime failure gates the path
        _TPU_COMPILE_STATUS = f"error: {type(e).__name__}: {str(e)[:300]}"
    return _TPU_COMPILE_STATUS


def int8_matmul(x, q, scale, out_dtype=None, interpret: bool | None = None,
                _probe: bool = False):
    """x [M, K] float; q [K, N] int8; scale [N] f32 -> [M, N].

    Equivalent to ``x @ (q * scale)`` with f32 accumulation. On TPU the
    weight tiles stream into VMEM as int8; elsewhere (or with
    ``interpret=True``) the same kernel runs under the Pallas interpreter.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    m, k = x.shape
    kq, n = q.shape
    assert k == kq and scale.shape == (n,), (x.shape, q.shape, scale.shape)
    out_dtype = out_dtype or x.dtype

    # shardlint: allow-mesh-rederivation(Pallas backend probe: asks which platform compiles, no mesh/device-world is derived)
    platform = jax.devices()[0].platform
    if interpret is None:
        interpret = False
    if not interpret and (
        platform != "tpu" or (not _probe and probe_tpu_compile() != "ok")
    ):
        # the Pallas interpreter is a test/debug vehicle only (orders of
        # magnitude slower); every non-TPU production platform — and a TPU
        # backend whose compile probe failed — takes the equivalent XLA
        # expression
        return (x.astype(jnp.float32) @ (q.astype(jnp.float32) * scale[None, :])).astype(out_dtype)

    tm, tn = _tile_sizes(m, n)
    pm = -(-m // tm) * tm
    pn = -(-n // tn) * tn
    # K is the int8 sublane dim of q and the lane dim of x: pad to 128 so
    # Mosaic tiling holds for any K (zero rows/cols contribute nothing)
    pk = -(-k // 128) * 128
    xp = jnp.pad(x, ((0, pm - m), (0, pk - k))) if (pm, pk) != (m, k) else x
    qp = jnp.pad(q, ((0, pk - k), (0, pn - n))) if (pk, pn) != (k, n) else q
    sp = jnp.pad(scale, (0, pn - n)) if pn != n else scale

    out = pl.pallas_call(
        _kernel,
        grid=(pm // tm, pn // tn),
        in_specs=[
            pl.BlockSpec((tm, pk), lambda i, j: (i, 0)),
            pl.BlockSpec((pk, tn), lambda i, j: (0, j)),
            pl.BlockSpec((tn,), lambda i, j: (j,)),
        ],
        out_specs=pl.BlockSpec((tm, tn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((pm, pn), out_dtype),
        interpret=interpret,
    )(xp, qp, sp)
    return out[:m, :n]


def int8_dense(x, qt, out_dtype=None):
    """Apply a quantized kernel (ops.quantize.QuantizedTensor holding a
    [K, N] weight) to activations [..., K] — reshapes to 2-D around the
    kernel so any leading batch structure works. Output dtype defaults to
    the weight's original dtype (matching dequantize_params semantics)."""
    out_dtype = out_dtype or qt.orig_dtype
    lead = x.shape[:-1]
    k = x.shape[-1]
    x2 = x.reshape((-1, k)) if lead else x.reshape((1, k))
    out = int8_matmul(x2, qt.q, qt.scale, out_dtype=out_dtype)
    n = out.shape[-1]
    return out.reshape((*lead, n)) if lead else out.reshape((n,))
