"""TPU-native ops: ring attention (sequence-parallel long context), sampling,
and pallas kernels. No reference counterpart — the reference is a serving
platform with no model/kernel code (SURVEY.md §5 'Long-context: absent,
design from scratch')."""
