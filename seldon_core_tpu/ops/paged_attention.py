"""Pallas TPU kernel: paged-attention decode read.

Why this kernel exists: the paged KV cache (models/transformer.py
``init_paged_kv_caches`` + runtime/batcher.py block tables) bills HBM for
pages actually written instead of ``max_len`` per slot — but the pure-XLA
fallback read still GATHERS the full logical view ([slots, n_pages*page_size])
back into a contiguous buffer before the attention einsum, i.e. it buys
capacity, not bandwidth. This kernel does what the gather cannot: for each
(sequence, page) grid step it streams exactly ONE page of K/V from HBM into
VMEM — addressed through the scalar-prefetched block table, the
vLLM/PagedAttention design (Kwon et al., SOSP 2023) — and accumulates the
masked softmax online, so the decode step's KV traffic is the pages the
block tables name, never the provisioned maximum.

Numerics: masking uses the pooled position rows exactly like the dense path
(PAD_POS slots get ``finfo(f32).min`` logits, contributing exact zeros), and
the online-softmax accumulation runs in f32. The kernel is NOT bit-identical
to the XLA einsum (different reduction order); the bit-exactness contract of
paged-vs-dense serving (tests/test_paged_kv.py) is carried by the gather
fallback, which IS the dense einsum on gathered bytes. Kernel parity tests
run interpret-mode under the ``pallas`` marker with tolerances.

Follows the ops/fused_norm.py probe/fallback pattern: on TPU a one-time
compile probe gates the compiled kernel; every other platform — or a TPU
whose probe fails — keeps the gather fallback inside models/transformer.py,
so the paged layout is safe to enable everywhere.
"""

from __future__ import annotations

import functools


def paged_attention_ref(q, cache, block_tables, positions):
    """Pure-XLA reference: gather the logical view through the block table
    (models/transformer.py ``gather_paged_view`` — the SAME gather the
    serving fallback uses, so the two can't drift) and run the dense
    masked-softmax einsum chain (identical op order to the in-line
    fallback's shared einsum). q: [b, 1, h, hd]; cache: the paged 3-tuple
    (bf16) or 5-tuple (int8) pool; block_tables: [b, n_pages];
    positions: [b, 1]. Returns [b, 1, h, hd] in q.dtype."""
    import jax
    import jax.numpy as jnp

    from seldon_core_tpu.models.transformer import gather_paged_view

    b, s, h, hd = q.shape
    dt = q.dtype
    k_all, v_all, pos_view = gather_paged_view(cache, block_tables, dt)
    kvh = k_all.shape[2]
    mask = pos_view[:, None, :] <= positions[:, :, None]  # [b, s, L]
    if kvh != h:
        rep = h // kvh
        k_all = jnp.repeat(k_all, rep, axis=2)
        v_all = jnp.repeat(v_all, rep, axis=2)
    scale = hd**-0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k_all.astype(dt)) * scale
    logits = logits.astype(jnp.float32)
    logits = jnp.where(mask[:, None, :, :], logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(dt)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v_all.astype(dt))


def _kernel(quantized: bool, n_pages: int, scale: float,
            bt_ref, qpos_ref, *refs):
    """Grid (b, n_pages): sequence i accumulates the online softmax over its
    block-table pages j (sequential axis). Scratch carries the running max,
    normalizer and weighted-value accumulator between pages."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if quantized:
        (q_ref, kq_ref, ks_ref, vq_ref, vs_ref, pos_ref, o_ref,
         m_ref, l_ref, acc_ref) = refs
        k = kq_ref[0].astype(jnp.float32) * ks_ref[0][..., None]
        v = vq_ref[0].astype(jnp.float32) * vs_ref[0][..., None]
    else:
        q_ref, k_ref, v_ref, pos_ref, o_ref, m_ref, l_ref, acc_ref = refs
        k = k_ref[0].astype(jnp.float32)   # [ps, kvh, hd]
        v = v_ref[0].astype(jnp.float32)
    i, j = pl.program_id(0), pl.program_id(1)

    q = q_ref[0].astype(jnp.float32)       # [h, hd]
    pos = pos_ref[0]                       # [ps]
    h, hd = q.shape
    ps, kvh, _ = k.shape
    if kvh != h:                           # GQA: repeat KV up to q heads
        k = jnp.repeat(k, h // kvh, axis=1)
        v = jnp.repeat(v, h // kvh, axis=1)

    neg = jnp.finfo(jnp.float32).min
    logits = jnp.einsum("hd,phd->hp", q, k,
                        preferred_element_type=jnp.float32) * scale
    mask = pos <= qpos_ref[i]              # [ps] — PAD_POS never attends
    logits = jnp.where(mask[None, :], logits, neg)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, neg)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    m_prev = m_ref[:, 0]                   # [h]
    l_prev = l_ref[:, 0]
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=-1))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(logits - m_new[:, None])   # [h, ps]
    l_new = l_prev * alpha + jnp.sum(p, axis=-1)
    acc = acc_ref[...] * alpha[:, None] + jnp.einsum(
        "hp,phd->hd", p, v, preferred_element_type=jnp.float32)
    m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new[:, None], l_ref.shape)
    acc_ref[...] = acc

    @pl.when(j == n_pages - 1)
    def _finish():
        o_ref[0] = (acc_ref[...] / l_ref[:, 0][:, None]).astype(o_ref.dtype)


_TPU_COMPILE_STATUS: str | None = None


def probe_tpu_compile(force: bool = False) -> str:
    """Attempt one tiny paged_attention Pallas compile+run on the TPU
    backend and cache the outcome for this process ("ok" or "error: ...").
    Backend Pallas support has flapped across rounds (ops/pallas_int8.py),
    so the serving path re-verifies on first TPU use and keeps the gather
    fallback when the kernel can't compile — the paged layout never
    surfaces a backend compile error."""
    global _TPU_COMPILE_STATUS
    if _TPU_COMPILE_STATUS is not None and not force:
        return _TPU_COMPILE_STATUS
    import jax
    import jax.numpy as jnp
    import numpy as np

    # shardlint: allow-mesh-rederivation(Pallas backend probe: asks which platform compiles, no mesh/device-world is derived)
    if jax.devices()[0].platform != "tpu":
        _TPU_COMPILE_STATUS = "error: no TPU backend in this process"
        return _TPU_COMPILE_STATUS
    try:
        from seldon_core_tpu.models.transformer import PAD_POS

        ps, hd = 8, 128
        pools = (jnp.zeros((3, ps, 1, hd), jnp.bfloat16),
                 jnp.zeros((3, ps, 1, hd), jnp.bfloat16),
                 jnp.full((3, ps), PAD_POS, jnp.int32))
        q = jnp.zeros((1, 1, 1, hd), jnp.bfloat16)
        bt = jnp.full((1, 1), 2, jnp.int32)
        out = paged_attention(q, pools, bt, jnp.zeros((1, 1), jnp.int32),
                              interpret=False, _probe=True)
        # graftlint: allow-host-sync-in-hot-path(one-time startup probe: the sync is the point — prove the kernel compiles AND runs before enabling the compiled path)
        np.asarray(out)
        _TPU_COMPILE_STATUS = "ok"
    except Exception as e:  # noqa: BLE001 — any compile/runtime failure gates the path
        _TPU_COMPILE_STATUS = f"error: {type(e).__name__}: {str(e)[:300]}"
    return _TPU_COMPILE_STATUS


def paged_kernel_viable() -> bool:
    """Trace-time gate the transformer's paged decode read uses: compiled
    Pallas path only on a TPU whose probe passed; everywhere else the
    gather fallback (which is the bit-exactness carrier) stays."""
    import jax

    # shardlint: allow-mesh-rederivation(Pallas backend probe: asks which platform compiles, no mesh/device-world is derived)
    return (jax.devices()[0].platform == "tpu"
            and probe_tpu_compile() == "ok")


def paged_attention(q, cache, block_tables, positions,
                    interpret: bool | None = None, _probe: bool = False):
    """q: [b, 1, h, hd]; cache: paged pool tuple (bf16 3-tuple or int8
    5-tuple, [pages, page_size, kvh, hd] buffers); block_tables: [b,
    n_pages] int32; positions: [b, 1] int32 query positions. Returns
    [b, 1, h, hd] in q.dtype.

    On TPU the read is one Pallas pass per (sequence, page) streaming only
    block-table-named pages; with ``interpret=True`` the same kernel runs
    under the Pallas interpreter (CI parity tests); any other platform
    takes the gather reference."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, s, h, hd = q.shape
    assert s == 1, "paged_attention is the decode (s=1) read"
    quantized = len(cache) == 5
    ps = cache[0].shape[1]
    n_pages = int(block_tables.shape[1])

    # shardlint: allow-mesh-rederivation(Pallas backend probe: asks which platform compiles, no mesh/device-world is derived)
    platform = jax.devices()[0].platform
    if interpret is None:
        interpret = False
    if not interpret and (
        platform != "tpu" or (not _probe and probe_tpu_compile() != "ok")
    ):
        return paged_attention_ref(q, cache, block_tables, positions)

    bt = jnp.asarray(block_tables, jnp.int32)
    qpos = jnp.asarray(positions, jnp.int32)[:, 0]  # [b]
    q3 = q[:, 0]                                    # [b, h, hd]

    def page_map(i, j, bt_ref, qpos_ref):
        return (bt_ref[i, j], 0, 0, 0)

    def scale_map(i, j, bt_ref, qpos_ref):
        return (bt_ref[i, j], 0, 0)

    def pos_map(i, j, bt_ref, qpos_ref):
        return (bt_ref[i, j], 0)

    def seq_map(i, j, bt_ref, qpos_ref):
        return (i, 0, 0)

    kvh = cache[0].shape[2]
    page_spec = lambda arr: pl.BlockSpec((1, ps, kvh, hd), page_map)  # noqa: E731
    if quantized:
        kq, ks, vq, vs, pos_pool = cache
        ins = [q3, kq, ks, vq, vs, pos_pool]
        in_specs = [
            pl.BlockSpec((1, h, hd), seq_map),
            page_spec(kq),
            pl.BlockSpec((1, ps, kvh), scale_map),
            page_spec(vq),
            pl.BlockSpec((1, ps, kvh), scale_map),
            pl.BlockSpec((1, ps), pos_map),
        ]
    else:
        k_pool, v_pool, pos_pool = cache
        ins = [q3, k_pool, v_pool, pos_pool]
        in_specs = [
            pl.BlockSpec((1, h, hd), seq_map),
            page_spec(k_pool),
            page_spec(v_pool),
            pl.BlockSpec((1, ps), pos_map),
        ]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,        # block tables + query positions
        grid=(b, n_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h, hd), seq_map),
        scratch_shapes=[
            pltpu.VMEM((h, 128), jnp.float32),  # running max
            pltpu.VMEM((h, 128), jnp.float32),  # running normalizer
            pltpu.VMEM((h, hd), jnp.float32),   # weighted-value accumulator
        ],
    )
    out = pl.pallas_call(
        functools.partial(_kernel, quantized, n_pages, hd**-0.5),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, hd), q.dtype),
        interpret=interpret,
    )(bt, qpos, *ins)
    return out[:, None]


__all__ = [
    "paged_attention",
    "paged_attention_ref",
    "paged_kernel_viable",
    "probe_tpu_compile",
]
