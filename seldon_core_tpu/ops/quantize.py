"""Int8 weight-only post-training quantization for serving.

TPU serving is usually HBM-bandwidth-bound; storing weights as int8 halves
the weight traffic vs bf16 while the MXU still computes in bf16: inside the
jitted forward each quantized leaf is dequantized as ``q.astype(bf16) *
scale`` and XLA fuses the convert+multiply into the consuming matmul/conv —
weights live in HBM as int8, dequant happens on the fly in VMEM. (The
reference's native-performance path delegates to TensorRT for this role;
here it is a first-class transform on any checkpoint.)

Scheme: symmetric per-output-channel int8 (scale = max|w| / 127 over all
dims but the last). 1-D leaves (biases, norms) and integer leaves pass
through unquantized — they are tiny and precision-critical.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import numpy as np


@dataclass
class QuantizedTensor:
    """int8 values + per-channel f32 scales (broadcast over the last dim).
    ``orig_dtype`` records the dtype dequantization restores (static pytree
    metadata, so one compiled program per dtype)."""

    q: Any  # int8 [..., C]
    scale: Any  # f32 [C]
    orig_dtype: str = "bfloat16"

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.q.dtype


def _register_pytree() -> None:
    import jax

    try:
        jax.tree_util.register_pytree_node(
            QuantizedTensor,
            lambda t: ((t.q, t.scale), t.orig_dtype),
            lambda aux, children: QuantizedTensor(*children, orig_dtype=aux),
        )
    except ValueError:
        pass  # already registered


def quantize_array(w, bits: int = 8):
    """Symmetric per-last-dim-channel quantization of one float array."""
    import jax.numpy as jnp

    qmax = 2 ** (bits - 1) - 1
    w = jnp.asarray(w)
    orig_dtype = str(w.dtype)
    reduce_dims = tuple(range(w.ndim - 1))
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=reduce_dims)
    scale = jnp.where(amax > 0, amax / qmax, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -qmax - 1, qmax).astype(jnp.int8)
    return QuantizedTensor(q=q, scale=scale, orig_dtype=orig_dtype)


def dequantize_array(t: QuantizedTensor, dtype=None):
    import jax.numpy as jnp

    dtype = jnp.dtype(dtype or t.orig_dtype)
    return t.q.astype(dtype) * t.scale.astype(dtype)


def _is_quantizable(leaf) -> bool:
    import jax.numpy as jnp

    dtype = getattr(leaf, "dtype", None)
    if dtype is None:
        return False
    # jnp.issubdtype, not np: bfloat16 (and float8) are ml_dtypes that numpy
    # classifies as void — np.issubdtype would silently skip bf16 checkpoints
    return jnp.issubdtype(jnp.dtype(str(dtype)), jnp.floating) and getattr(leaf, "ndim", 0) >= 2


def quantize_params(params: Any, bits: int = 8) -> Any:
    """Quantize every ≥2-D float leaf of a param pytree; the rest passes
    through. Returns a tree mixing QuantizedTensor and original leaves."""
    import jax

    _register_pytree()

    def visit(leaf):
        return quantize_array(leaf, bits) if _is_quantizable(leaf) else leaf

    return jax.tree.map(visit, params)


def dequantize_params(params: Any, dtype=None) -> Any:
    """Inverse transform, used INSIDE the jitted forward so XLA fuses the
    dequant into consumers (int8 stays the HBM format)."""
    import jax

    _register_pytree()

    def visit(leaf):
        return dequantize_array(leaf, dtype) if isinstance(leaf, QuantizedTensor) else leaf

    return jax.tree.map(visit, params, is_leaf=lambda x: isinstance(x, QuantizedTensor))


def quantized_matmul(x, qt: QuantizedTensor, out_dtype=None):
    """Public int8-weight matmul for user components.

    Serving path is the XLA-fused dequant expression on every backend: the
    round-4 decision bench on the real chip (tpu_sweep_results.jsonl
    int8-gemm-*, 2026-07-30) measured the explicit Pallas kernel at
    0.55-0.79x the fused XLA expression on the decode GEMM shapes now that
    the backend accepts Pallas at all — XLA's fusion of convert+multiply
    into the consuming matmul beats the hand-tiled schedule here. The
    kernel stays available as ``ops.pallas_int8.int8_dense`` (probe-gated)
    for explicit experiments."""
    out_dtype = out_dtype or qt.orig_dtype
    # dequant in the activation dtype (the compute dtype): XLA fuses the
    # convert+multiply into the matmul, weights stay int8 in HBM
    return (x @ dequantize_array(qt, x.dtype)).astype(out_dtype)


def quantized_bytes(params: Any) -> int:
    """HBM footprint of the (possibly mixed) tree — for reporting.

    Metadata-only on purpose: sizing from shape/dtype never touches the
    buffers, where the old ``np.asarray(leaf)`` pulled the ENTIRE tree
    (gigabytes at 7B) through the host just to read ``.size`` — a
    device->host sync per leaf (graftlint: host-sync-in-hot-path).
    """
    import math

    import jax

    _register_pytree()
    total = 0
    for leaf in jax.tree.leaves(params):
        shape = getattr(leaf, "shape", ())
        dtype = getattr(leaf, "dtype", None)
        itemsize = np.dtype(dtype).itemsize if dtype is not None else 8
        total += math.prod(shape) * itemsize
    return total
