"""Pallas TPU kernel: fused residual-add + RMSNorm for LLM decode.

Why this kernel exists: the round-5 decode profile (benchmarks/
DECODE_NOTES.md) attributes 18% of device time to ~899 RMSNorm-rooted
fusion clusters averaging 7.5 us each on [8, 2048] tensors that should take
<1 us of bandwidth — at batch 8 the decode step is per-op-overhead-bound,
and the named lever is fewer/larger kernels per step. Each transformer
block runs ``x = x + h`` followed by ``rms_norm(x)``: two HBM round trips
of the activation. This kernel computes both in ONE pass — read x and h
once, write the residual sum and the normed activation once, the f32
mean-of-squares reduction entirely in VMEM.

Numerics contract (bit-matching the unfused graph so the
``TransformerConfig.fused_norm`` flag never changes tokens): the residual
add happens in the model dtype, the norm in f32 over the added value, the
weight multiply in f32, the result cast back to the model dtype — exactly
``rms_norm(x + h, w, eps)`` from models/transformer.py.

Follows the ops/pallas_int8.py probe/fallback pattern: ``interpret=True``
runs the kernel body under the Pallas interpreter (CI parity tests, CPU);
on TPU a one-time compile probe gates the compiled kernel, and every other
platform — or a TPU whose probe fails — takes the equivalent XLA
expression (``residual_rmsnorm_ref``), so the flag is safe to leave on.
"""

from __future__ import annotations

import functools


def residual_rmsnorm_ref(x, h, weight, eps: float):
    """Pure-XLA reference: (y, rms_norm(y, weight, eps)) with y = x + h.
    Identical op chain to the unfused TransformerBlock path."""
    import jax
    import jax.numpy as jnp

    y = x + h
    y32 = y.astype(jnp.float32)
    norm = y32 * jax.lax.rsqrt(jnp.mean(y32 * y32, axis=-1, keepdims=True) + eps)
    return y, (norm * weight).astype(y.dtype)


def _kernel(d_real: int, eps: float, x_ref, h_ref, w_ref, y_ref, o_ref):
    import jax
    import jax.numpy as jnp

    y = x_ref[...] + h_ref[...]  # residual add in the model dtype
    y_ref[...] = y
    y32 = y.astype(jnp.float32)
    # sum/d_real, not mean: the lane dim may be zero-padded to 128 and the
    # padded columns must not dilute the divisor (zeros already add nothing
    # to the sum)
    ms = jnp.sum(y32 * y32, axis=-1, keepdims=True) * (1.0 / d_real)
    normed = y32 * jax.lax.rsqrt(ms + eps)
    o_ref[...] = (normed * w_ref[...].astype(jnp.float32)[None, :]).astype(o_ref.dtype)


_TPU_COMPILE_STATUS: str | None = None


def probe_tpu_compile(force: bool = False) -> str:
    """Attempt one tiny fused_residual_rmsnorm Pallas compile+run on the TPU
    backend and cache the outcome for this process ("ok" or "error: ...").
    Backend Pallas support has flapped across rounds (see
    ops/pallas_int8.py), so the serving path re-verifies on first TPU use
    and falls back to the XLA expression when the kernel can't compile —
    the fused_norm flag never surfaces a backend compile error."""
    global _TPU_COMPILE_STATUS
    if _TPU_COMPILE_STATUS is not None and not force:
        return _TPU_COMPILE_STATUS
    import jax
    import jax.numpy as jnp
    import numpy as np

    # shardlint: allow-mesh-rederivation(Pallas backend probe: asks which platform compiles, no mesh/device-world is derived)
    if jax.devices()[0].platform != "tpu":
        _TPU_COMPILE_STATUS = "error: no TPU backend in this process"
        return _TPU_COMPILE_STATUS
    try:
        x = jnp.zeros((8, 128), jnp.bfloat16)
        w = jnp.ones((128,), jnp.float32)
        y, o = fused_residual_rmsnorm(x, x, w, 1e-5, interpret=False, _probe=True)
        # graftlint: allow-host-sync-in-hot-path(one-time startup probe: the sync is the point — prove the kernel compiles AND runs before enabling the compiled path)
        np.asarray(o)
        _TPU_COMPILE_STATUS = "ok"
    except Exception as e:  # noqa: BLE001 — any compile/runtime failure gates the path
        _TPU_COMPILE_STATUS = f"error: {type(e).__name__}: {str(e)[:300]}"
    return _TPU_COMPILE_STATUS


def fused_residual_rmsnorm(x, h, weight, eps: float,
                           interpret: bool | None = None,
                           _probe: bool = False):
    """x, h: [..., d] activations; weight: [d] f32. Returns
    (y, normed) = (x + h, rms_norm(x + h, weight, eps)), both in x.dtype.

    On TPU the whole computation is one Pallas pass (one HBM read of x/h,
    one write of each output); elsewhere — or with ``interpret=True`` — the
    same kernel runs under the Pallas interpreter, and non-TPU production
    platforms take the equivalent XLA expression.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    d = x.shape[-1]
    assert h.shape == x.shape and weight.shape == (d,), (x.shape, h.shape, weight.shape)

    # shardlint: allow-mesh-rederivation(Pallas backend probe: asks which platform compiles, no mesh/device-world is derived)
    platform = jax.devices()[0].platform
    if interpret is None:
        interpret = False
    if not interpret and (
        platform != "tpu" or (not _probe and probe_tpu_compile() != "ok")
    ):
        # the Pallas interpreter is a test/debug vehicle only; every non-TPU
        # production platform — and a TPU backend whose compile probe failed
        # — takes the equivalent XLA expression
        return residual_rmsnorm_ref(x, h, weight, eps)

    lead = x.shape[:-1]
    x2 = x.reshape(-1, d)
    h2 = h.reshape(-1, d)
    m = x2.shape[0]
    # sublane tile shrinks for small (decode) batches but stays a multiple
    # of the min f32 tile (8); lane dim pads to 128 for Mosaic tiling
    tm = 256 if m >= 256 else max(8, 1 << max(m - 1, 0).bit_length())
    pm = -(-m // tm) * tm
    pd = -(-d // 128) * 128
    if (pm, pd) != (m, d):
        x2 = jnp.pad(x2, ((0, pm - m), (0, pd - d)))
        h2 = jnp.pad(h2, ((0, pm - m), (0, pd - d)))
    w = weight.astype(jnp.float32)
    if pd != d:
        w = jnp.pad(w, (0, pd - d))

    y, o = pl.pallas_call(
        functools.partial(_kernel, d, float(eps)),
        grid=(pm // tm,),
        in_specs=[
            pl.BlockSpec((tm, pd), lambda i: (i, 0)),
            pl.BlockSpec((tm, pd), lambda i: (i, 0)),
            pl.BlockSpec((pd,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((tm, pd), lambda i: (i, 0)),
            pl.BlockSpec((tm, pd), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((pm, pd), x.dtype),
            jax.ShapeDtypeStruct((pm, pd), x.dtype),
        ],
        interpret=interpret,
    )(x2, h2, w)
    if (pm, pd) != (m, d):
        y, o = y[:m, :d], o[:m, :d]
    return y.reshape(*lead, d), o.reshape(*lead, d)


__all__ = [
    "fused_residual_rmsnorm",
    "residual_rmsnorm_ref",
    "probe_tpu_compile",
]
