"""Ring attention: exact attention over sequence-sharded Q/K/V.

Long-context sequence parallelism for the transformer family: Q, K, V live
sharded along the sequence axis of a device mesh; each device computes
attention of its local query block against one K/V block at a time while the
K/V blocks rotate around the ring via ``ppermute`` (one ICI hop per step, so
communication overlaps compute and no device ever holds the full sequence).
Softmax is accumulated online flash-style (running max/denominator), so the
result is exact, not approximate.

The reference has no analogue (SURVEY.md §5: long-context/sequence
parallelism "absent — design from scratch"); the design follows the public
ring-attention recipe (blockwise attention + rotating KV; see PAPERS.md).

Layout convention: q/k/v are [batch, seq, heads, head_dim]; positions are
[batch, seq] absolute indices (needed for causal masking across blocks —
after sharding, a device only knows global causality through positions).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from seldon_core_tpu.parallel.compat import axis_size, shard_map

NEG_INF = jnp.finfo(jnp.float32).min


def _block_attention(q, k_blk, v_blk, q_pos, kv_pos, m, l, acc, scale, causal):
    """One online-softmax accumulation step of local q against one K/V block.

    GQA-aware: q is [b, sq, hk, g, d] (query heads grouped per KV head, so
    only the *unrepeated* KV rotates the ring); k_blk/v_blk: [b, sk, hk, d];
    q_pos: [b, sq]; kv_pos: [b, sk]; m, l: [b, hk, g, sq] running max /
    denominator; acc: [b, sq, hk, g, d] running numerator.
    """
    logits = jnp.einsum("bqhgd,bkhd->bhgqk", q, k_blk).astype(jnp.float32) * scale
    if causal:
        mask = kv_pos[:, None, None, None, :] <= q_pos[:, None, None, :, None]
        logits = jnp.where(mask, logits, NEG_INF)

    blk_max = jnp.max(logits, axis=-1)  # [b, hk, g, sq]
    m_new = jnp.maximum(m, blk_max)
    # Fully-masked-so-far rows keep m == NEG_INF; exp guards avoid inf-inf.
    p = jnp.exp(logits - m_new[..., None])
    p = jnp.where(logits <= NEG_INF, 0.0, p)
    corr = jnp.where(m <= NEG_INF, 0.0, jnp.exp(m - m_new))

    l_new = l * corr + jnp.sum(p, axis=-1)
    acc_new = acc * corr.transpose(0, 3, 1, 2)[..., None] + jnp.einsum(
        "bhgqk,bkhd->bqhgd", p, v_blk.astype(jnp.float32)
    )
    return m_new, l_new, acc_new


def _ring_attention_local(q, k, v, q_pos, kv_pos, axis_name: Optional[str], causal: bool):
    """Per-device body: rotate K/V around `axis_name` accumulating attention.
    With axis_name=None this degenerates to single-block (full) attention.
    q: [b, sq, h, d]; k/v: [b, sk, hk, d] with h % hk == 0 (GQA) — only the
    unrepeated KV travels the ring."""
    b, sq, h, d = q.shape
    hk = k.shape[2]
    g = h // hk
    q = q.reshape(b, sq, hk, g, d)
    scale = d**-0.5
    m = jnp.full((b, hk, g, sq), NEG_INF, jnp.float32)
    l = jnp.zeros((b, hk, g, sq), jnp.float32)
    acc = jnp.zeros((b, sq, hk, g, d), jnp.float32)

    if axis_name is None:
        m, l, acc = _block_attention(q, k, v, q_pos, kv_pos, m, l, acc, scale, causal)
    else:
        n = axis_size(axis_name)
        perm = [(i, (i + 1) % n) for i in range(n)]
        # exactly 3 rotating buffers (k, v, kv positions) => exactly 3
        # collective-permutes in the compiled loop body — a CI-enforced
        # budget (tools/hlolint ops.ring_attention_seq8); a new rotating
        # carry must update that contract alongside this code

        def step(i, carry):
            k_blk, v_blk, kvp, m, l, acc = carry
            m, l, acc = _block_attention(q, k_blk, v_blk, q_pos, kvp, m, l, acc, scale, causal)
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
            kvp = jax.lax.ppermute(kvp, axis_name, perm)
            return k_blk, v_blk, kvp, m, l, acc

        _, _, _, m, l, acc = jax.lax.fori_loop(0, n, step, (k, v, kv_pos, m, l, acc))

    denom = jnp.maximum(l, jnp.finfo(jnp.float32).tiny).transpose(0, 3, 1, 2)[..., None]
    return (acc / denom).reshape(b, sq, h, d).astype(q.dtype)


def ring_attention(
    q,
    k,
    v,
    q_positions,
    kv_positions,
    mesh=None,
    seq_axis: str = "seq",
    batch_axis: Optional[str] = "data",
    head_axis: Optional[str] = "model",
    causal: bool = True,
):
    """Exact attention over seq-sharded q/k/v on ``mesh``.

    GQA-aware: k/v may carry fewer heads than q (h % hk == 0) and are rotated
    *unrepeated*, so ring ICI traffic and per-device KV memory stay at the
    grouped size. Without a mesh (or when the mesh lacks ``seq_axis``) this is
    plain full attention — callers can use one code path everywhere.
    """
    if mesh is None or seq_axis not in getattr(mesh, "axis_names", ()):
        return _ring_attention_local(q, k, v, q_positions, kv_positions, None, causal)

    ba = batch_axis if batch_axis in mesh.axis_names else None
    ha = head_axis if head_axis in mesh.axis_names else None
    qkv_spec = P(ba, seq_axis, ha, None)
    pos_spec = P(ba, seq_axis)

    fn = shard_map(
        partial(_ring_attention_local, axis_name=seq_axis, causal=causal),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, pos_spec, pos_spec),
        out_specs=qkv_spec,
        check_rep=False,
    )
    return fn(q, k, v, q_positions, kv_positions)
