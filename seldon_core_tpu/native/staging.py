"""ctypes binding for the shared-memory staging ring (native/ring.cc).

``SharedRing`` is the IPC data plane between transport worker processes and
the device-owning engine process: lock-free MPMC, payloads are raw bytes (the
codec's packed tensors), one memcpy per side. The .so builds lazily via make
with the baked-in g++ (pybind11 is unavailable in this environment; ctypes
keeps the binding dependency-free).
"""

from __future__ import annotations

import ctypes
import logging
import os
import struct
import subprocess
import threading
import time
from typing import Optional

_U32 = struct.Struct("<I")

logger = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "build", "libseldon_staging.so")

_lib = None
_lib_lock = threading.Lock()


def build_native(force: bool = False) -> str:
    """Build the native library if needed; returns the .so path."""
    if os.path.exists(_SO_PATH) and not force:
        src_mtime = os.path.getmtime(os.path.join(_NATIVE_DIR, "ring.cc"))
        if os.path.getmtime(_SO_PATH) >= src_mtime:
            return _SO_PATH
    subprocess.run(["make", "-C", _NATIVE_DIR], check=True, capture_output=True)
    return _SO_PATH


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        path = build_native()
        lib = ctypes.CDLL(path)
        lib.scr_create.restype = ctypes.c_void_p
        lib.scr_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64]
        lib.scr_attach.restype = ctypes.c_void_p
        lib.scr_attach.argtypes = [ctypes.c_char_p]
        lib.scr_detach.argtypes = [ctypes.c_void_p]
        lib.scr_capacity.restype = ctypes.c_uint64
        lib.scr_capacity.argtypes = [ctypes.c_void_p]
        lib.scr_slot_size.restype = ctypes.c_uint64
        lib.scr_slot_size.argtypes = [ctypes.c_void_p]
        lib.scr_size.restype = ctypes.c_uint64
        lib.scr_size.argtypes = [ctypes.c_void_p]
        lib.scr_push.restype = ctypes.c_int
        lib.scr_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
        lib.scr_pop.restype = ctypes.c_int
        lib.scr_pop.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint32]
        lib.scr_pop_many.restype = ctypes.c_int
        lib.scr_pop_many.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.scr_push_model_resps.restype = ctypes.c_int
        lib.scr_push_model_resps.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,  # req_ids u32*
            ctypes.c_void_p,  # row_offsets u64*
            ctypes.c_void_p,  # row_counts u32*
            ctypes.c_uint32,  # n
            ctypes.c_void_p,  # data f8*
            ctypes.c_uint64,  # row_nvals
            ctypes.c_void_p,  # tail_dims u32*
            ctypes.c_uint32,  # n_tail
            ctypes.c_char_p,  # frag
            ctypes.c_uint32,  # frag_len
            ctypes.c_uint32,  # dtype_code
        ]
        _lib = lib
        return lib


def native_available() -> bool:
    try:
        _load()
        return True
    except Exception as e:  # toolchain missing
        logger.warning("native staging unavailable: %s", e)
        return False


class RingFull(RuntimeError):
    pass


class PayloadTooLarge(ValueError):
    pass


class SharedRing:
    """MPMC shared-memory byte queue over a mapped file.

    create=True initialises the file (the engine side does this); workers
    attach to the same path. Capacity must be a power of two.
    """

    def __init__(self, path: str, capacity: int = 1024, slot_size: int = 1 << 20,
                 create: bool = False):
        self._lib = _load()
        self.path = path
        if create:
            self._h = self._lib.scr_create(path.encode(), capacity, slot_size)
        else:
            self._h = self._lib.scr_attach(path.encode())
        if not self._h:
            raise RuntimeError(f"could not {'create' if create else 'attach'} ring at {path}")
        self.capacity = int(self._lib.scr_capacity(self._h))
        self.slot_size = int(self._lib.scr_slot_size(self._h))
        self._popbuf = ctypes.create_string_buffer(self.slot_size)
        self._manybuf = None  # lazy (pop_many only; engine-side)

    # ------------------------------------------------------------------
    def push(self, payload: bytes) -> bool:
        """True on success, False when full; raises PayloadTooLarge."""
        rc = self._lib.scr_push(self._h, payload, len(payload))
        if rc == 0:
            return True
        if rc == -1:
            return False
        raise PayloadTooLarge(f"{len(payload)} bytes > slot_size {self.slot_size}")

    def push_wait(self, payload: bytes, timeout_s: float = 1.0, spin_s: float = 0.0002) -> None:
        deadline = time.monotonic() + timeout_s
        while not self.push(payload):
            if time.monotonic() > deadline:
                raise RingFull(f"ring {self.path} full for {timeout_s}s")
            time.sleep(spin_s)

    def pop(self) -> Optional[bytes]:
        """One payload or None when empty."""
        rc = self._lib.scr_pop(self._h, self._popbuf, self.slot_size)
        if rc >= 0:
            # string_at copies exactly rc bytes; _popbuf.raw[:rc] would
            # materialise the full slot (1MB) per pop — measured as ~2/3 of
            # the engine's CPU at 7k rps
            return ctypes.string_at(self._popbuf, rc)
        if rc == -1:
            return None
        raise RuntimeError(f"ring pop error {rc}")

    def pop_batch(self, max_items: int, wait_s: float = 0.0, spin_s: float = 0.0002):
        """Drain up to max_items; optionally wait up to wait_s for the first."""
        out = []
        deadline = time.monotonic() + wait_s
        while len(out) < max_items:
            item = self.pop()
            if item is None:
                if out or time.monotonic() > deadline:
                    break
                time.sleep(spin_s)
                continue
            out.append(item)
        return out

    def pop_many(self, max_items: int, wait_s: float = 0.0, spin_s: float = 0.0002):
        """Batched drain: ONE FFI call pops up to max_items frames into the
        reusable pop buffer and returns zero-copy memoryview slices into it.

        The views are valid only until the next pop/pop_many on this ring —
        callers must finish with (or copy) each frame within the drain
        cycle. Falls back timing-wise like pop_batch: waits up to wait_s for
        the first frame."""
        if self._manybuf is None:
            # slot_size + 4 guarantees the largest possible frame always
            # fits (progress), the extra room batches typical small frames
            self._manybuf = ctypes.create_string_buffer(self.slot_size + 4 + (256 << 10))
        used = ctypes.c_uint32(0)
        deadline = time.monotonic() + wait_s
        while True:
            n = self._lib.scr_pop_many(
                self._h, self._manybuf, len(self._manybuf), max_items,
                ctypes.byref(used))
            if n > 0:
                break
            if n == -3:
                # non-empty ring whose first frame exceeds our buffer: the
                # sizing above makes this impossible (slot_size + 4 always
                # fits), so spinning would loop forever on a real bug
                raise RuntimeError(
                    "scr_pop_many: pending frame larger than drain buffer "
                    f"({len(self._manybuf)} bytes) — ring slot_size mismatch")
            if time.monotonic() > deadline:
                return []
            time.sleep(spin_s)
        # ctypes buffers expose format 'c' memoryviews, whose item access
        # returns 1-byte bytes (and struct/int indexing raises); cast to 'B'.
        # Read-only: np.frombuffer over these views must yield read-only
        # arrays so an in-place-mutating component fails fast (as it did
        # with pop_batch's bytes) instead of scribbling over the shared
        # drain buffer under other frames.
        mv = memoryview(self._manybuf).cast("B").toreadonly()
        out = []
        off = 0
        for _ in range(n):
            (length,) = _U32.unpack_from(mv, off)
            out.append(mv[off + 4:off + 4 + length])
            off += 4 + length
        return out

    def push_model_resps(self, req_ids, row_offsets, row_counts, data,
                         row_nvals: int, tail_dims, frag: bytes,
                         dtype_code: int, timeout_s: float = 5.0,
                         spin_s: float = 0.0002) -> None:
        """Bulk kind-2 OK response push: the C side builds each response
        frame directly in its ring slot (ModelExecutor._ok_response layout)
        from one stacked f8 row buffer. Retries the unpushed tail when the
        ring is momentarily full; raises RingFull past timeout_s and
        PayloadTooLarge when a response exceeds the slot."""
        import numpy as np

        req_ids = np.ascontiguousarray(req_ids, dtype=np.uint32)
        row_offsets = np.ascontiguousarray(row_offsets, dtype=np.uint64)
        row_counts = np.ascontiguousarray(row_counts, dtype=np.uint32)
        tail = np.ascontiguousarray(tail_dims, dtype=np.uint32)
        if data.dtype != np.float64 or not data.flags.c_contiguous:
            raise ValueError("push_model_resps needs C-contiguous float64 rows")
        # pre-check EVERY response against the slot size so the C call can
        # never commit a partial batch and then fail (-2 after i pushes
        # would leave pushed frames to be answered AGAIN by the fallback)
        head = 7 + 4 * (1 + len(tail)) + 4 + len(frag)
        if int(row_counts.max(initial=0)) * row_nvals * 8 + head > self.slot_size:
            raise PayloadTooLarge(
                f"model response exceeds slot_size {self.slot_size}")
        deadline = time.monotonic() + timeout_s
        start = 0
        n = len(req_ids)
        while start < n:
            rc = self._lib.scr_push_model_resps(
                self._h,
                req_ids[start:].ctypes.data, row_offsets[start:].ctypes.data,
                row_counts[start:].ctypes.data, n - start,
                data.ctypes.data, row_nvals,
                tail.ctypes.data, len(tail), frag, len(frag), dtype_code)
            if rc == -2:
                raise PayloadTooLarge(
                    f"model response exceeds slot_size {self.slot_size}")
            start += rc
            if start < n:
                if time.monotonic() > deadline:
                    raise RingFull(f"ring {self.path} full for {timeout_s}s")
                time.sleep(spin_s)

    def __len__(self) -> int:
        return int(self._lib.scr_size(self._h))

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.scr_detach(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass
