"""Native runtime components (C++ via ctypes).

The reference keeps its native performance path in external C++ servers
(SURVEY.md §2 native-code note); this package keeps it in-repo. The library
builds on demand with the baked-in toolchain (g++) and callers get a clear
error if the toolchain is missing.
"""

from seldon_core_tpu.native.staging import (
    PayloadTooLarge,
    RingFull,
    SharedRing,
    build_native,
    native_available,
)

__all__ = ["PayloadTooLarge", "RingFull", "SharedRing", "build_native", "native_available"]
