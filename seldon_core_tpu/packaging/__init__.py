"""Container packaging: wrap user model code into a servable image.

The tpu-native equivalent of the reference's s2i python wrapper pipeline
(`wrappers/s2i/python/s2i/bin/assemble` + `run` + `Dockerfile.tmpl`):
instead of source-to-image injection, `wrap` layers the user's model
directory onto the engine image and bakes the microservice invocation the
s2i `run` script would have exec'd.
"""

from seldon_core_tpu.packaging.wrap import (  # noqa: F401
    containerfile_for_model,
    detect_runtime,
    wrap_model,
)
