"""Model-artifact fetching from object stores.

Capability of the reference's `python/seldon_core/storage.py:36-160` (gs://,
s3://, azure, file://, local). In this environment only local/file paths can
be exercised; cloud schemes are implemented behind lazy imports and raise a
clear error when the SDK is absent.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from typing import Optional
from urllib.parse import urlparse


class StorageError(RuntimeError):
    pass


def download(uri: str, out_dir: Optional[str] = None) -> str:
    """Fetch a model artifact directory/file to local disk, returning the path."""
    parsed = urlparse(uri)
    scheme = parsed.scheme
    if scheme in ("", "file"):
        return _local(parsed.path if scheme == "file" else uri, out_dir)
    if scheme == "gs":
        return _gcs(parsed, out_dir)
    if scheme == "s3":
        return _s3(parsed, out_dir)
    if scheme in ("http", "https"):
        if parsed.netloc.endswith(".blob.core.windows.net"):
            return _azure_blob(parsed, out_dir)
        return _http(uri, out_dir)
    raise StorageError(f"Unsupported model URI scheme {scheme!r} in {uri!r}")


def _local(path: str, out_dir: Optional[str]) -> str:
    path = os.path.abspath(path)
    if not os.path.exists(path):
        raise StorageError(f"Local model path does not exist: {path}")
    if out_dir is None:
        return path
    os.makedirs(out_dir, exist_ok=True)
    if os.path.isdir(path):
        dst = os.path.join(out_dir, os.path.basename(path.rstrip("/")))
        if not os.path.exists(dst):
            shutil.copytree(path, dst)
        return dst
    dst = os.path.join(out_dir, os.path.basename(path))
    shutil.copy2(path, dst)
    return dst


def _workdir(out_dir: Optional[str]) -> str:
    if out_dir is None:
        out_dir = tempfile.mkdtemp(prefix="seldon-tpu-model-")
    os.makedirs(out_dir, exist_ok=True)
    return out_dir


def _safe_rel(name: str, prefix: str) -> Optional[str]:
    """Destination-relative path for a listed object, or None to skip it.

    Object listings are remote-controlled input: a prefix query for
    ``models/llm`` also matches ``models/llm2/x``, whose relpath would be
    ``../llm2/x`` — a path traversal out of the download dir. Treat the
    prefix as a *directory boundary*: only the exact object or objects
    under ``prefix/`` qualify."""
    if not prefix:
        return name
    if name == prefix:
        return os.path.basename(name)
    boundary = prefix if prefix.endswith("/") else prefix + "/"
    if not name.startswith(boundary):
        return None
    return name[len(boundary):]


def _safe_dst(out_dir: str, name: str, prefix: str) -> Optional[str]:
    """Containment-checked local destination for object ``name``; None if
    the object falls outside the prefix boundary or would escape out_dir."""
    rel = _safe_rel(name, prefix)
    if rel is None or not rel or rel.endswith("/"):
        return None
    dst = os.path.join(out_dir, rel)
    root = os.path.realpath(out_dir)
    if not os.path.realpath(dst).startswith(root + os.sep):
        return None
    os.makedirs(os.path.dirname(dst) or out_dir, exist_ok=True)
    return dst


def _gcs(parsed, out_dir: Optional[str]) -> str:
    try:
        from google.cloud import storage as gcs  # type: ignore
    except ImportError as e:
        raise StorageError(
            "gs:// model URIs require google-cloud-storage, which is not installed"
        ) from e
    out_dir = _workdir(out_dir)
    try:
        client = gcs.Client()
    except Exception:
        client = gcs.Client.create_anonymous_client()
    bucket = client.bucket(parsed.netloc)
    prefix = parsed.path.lstrip("/")
    count = 0
    for blob in bucket.list_blobs(prefix=prefix):
        dst = _safe_dst(out_dir, blob.name, prefix)
        if dst is None:
            continue
        blob.download_to_filename(dst)
        count += 1
    if count == 0:
        raise StorageError(f"No objects found at gs://{parsed.netloc}/{prefix}")
    return out_dir


def _s3(parsed, out_dir: Optional[str]) -> str:
    try:
        import boto3  # type: ignore
    except ImportError as e:
        raise StorageError("s3:// model URIs require boto3, which is not installed") from e
    out_dir = _workdir(out_dir)
    s3 = boto3.client(
        "s3",
        endpoint_url=os.environ.get("S3_ENDPOINT") or None,
        aws_access_key_id=os.environ.get("AWS_ACCESS_KEY_ID"),
        aws_secret_access_key=os.environ.get("AWS_SECRET_ACCESS_KEY"),
    )
    prefix = parsed.path.lstrip("/")
    count = 0
    paginator = s3.get_paginator("list_objects_v2")
    for page in paginator.paginate(Bucket=parsed.netloc, Prefix=prefix):
        for obj in page.get("Contents", []):
            dst = _safe_dst(out_dir, obj["Key"], prefix)
            if dst is None:
                continue
            s3.download_file(parsed.netloc, obj["Key"], dst)
            count += 1
    if count == 0:
        raise StorageError(f"No objects found at s3://{parsed.netloc}/{prefix}")
    return out_dir


def _azure_blob(parsed, out_dir: Optional[str]) -> str:
    """``https://<account>.blob.core.windows.net/<container>/<prefix>``
    (the reference's `storage.py:109-128` _download_blob, modernized to the
    ``azure-storage-blob`` ContainerClient API). Credentials: the
    ``AZURE_STORAGE_CONNECTION_STRING`` env var when set, else anonymous
    (public containers, matching the reference's credential-less
    BlockBlobService default)."""
    try:
        from azure.storage.blob import ContainerClient  # type: ignore
    except ImportError as e:
        raise StorageError(
            "azure blob model URIs require azure-storage-blob, which is not installed"
        ) from e
    path = parsed.path.lstrip("/")
    if "/" not in path:
        container, prefix = path, ""
    else:
        container, prefix = path.split("/", 1)
    if not container:
        raise StorageError(f"Azure blob URI needs a container: {parsed.geturl()!r}")
    conn = os.environ.get("AZURE_STORAGE_CONNECTION_STRING")
    if conn:
        client = ContainerClient.from_connection_string(conn, container_name=container)
    else:
        client = ContainerClient(
            account_url=f"https://{parsed.netloc}", container_name=container
        )
    out_dir = _workdir(out_dir)
    count = 0
    for blob in client.list_blobs(name_starts_with=prefix):
        name = getattr(blob, "name", None) or blob["name"]
        dst = _safe_dst(out_dir, name, prefix)
        if dst is None:
            continue
        with open(dst, "wb") as f:
            client.download_blob(name).readinto(f)
        count += 1
    if count == 0:
        raise StorageError(
            f"No blobs found at https://{parsed.netloc}/{container}/{prefix}")
    return out_dir


def _http(uri: str, out_dir: Optional[str]) -> str:
    import requests

    out_dir = _workdir(out_dir)
    dst = os.path.join(out_dir, os.path.basename(urlparse(uri).path) or "model")
    with requests.get(uri, stream=True, timeout=60) as r:
        r.raise_for_status()
        with open(dst, "wb") as f:
            for chunk in r.iter_content(1 << 20):
                f.write(chunk)
    return dst
