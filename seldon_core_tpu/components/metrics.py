"""In-band custom metric helpers.

Components return a list of metric dicts from ``metrics()``; they flow through
the response ``meta.metrics`` and are registered by the engine — the
reference's distinctive metrics-in-the-payload design
(`python/seldon_core/metrics.py:8-89`, `proto/prediction.proto:48-58`).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

COUNTER = "COUNTER"
GAUGE = "GAUGE"
TIMER = "TIMER"
_TYPES = (COUNTER, GAUGE, TIMER)


def create_counter(key: str, value: float, tags: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
    return _metric(key, COUNTER, value, tags)


def create_gauge(key: str, value: float, tags: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
    return _metric(key, GAUGE, value, tags)


def create_timer(key: str, value: float, tags: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
    return _metric(key, TIMER, value, tags)


def _metric(key: str, mtype: str, value: float, tags: Optional[Dict[str, str]]) -> Dict[str, Any]:
    d: Dict[str, Any] = {"key": key, "type": mtype, "value": value}
    if tags:
        d["tags"] = tags
    return d


def validate_metrics(metrics: Any) -> bool:
    """Schema check mirroring the reference (`python/seldon_core/metrics.py:60-89`):
    a list of {key: str, type: COUNTER|GAUGE|TIMER, value: number}."""
    if not isinstance(metrics, (list, tuple)):
        return False
    for m in metrics:
        if not isinstance(m, dict):
            return False
        if not isinstance(m.get("key"), str):
            return False
        if m.get("type") not in _TYPES:
            return False
        v = m.get("value")
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            return False
    return True
