"""The user component contract.

Capability parity with the reference's ``SeldonComponent``
(`python/seldon_core/user_model.py:12-72`): high-level methods receive
arrays/bytes/str plus feature names and meta; ``*_raw`` escape hatches receive
the full SeldonMessage; ``metrics()``/``tags()``/``class_names()``/
``feature_names()`` enrich responses.

TPU-first addition: a component may expose ``jax_fn()`` returning a pure,
jittable ``fn(params, x) -> y`` plus params. The engine uses it to fuse the
whole graph into one XLA computation and to shard it over a device mesh —
something the reference's process-per-node design cannot do.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from seldon_core_tpu.components.metrics import validate_metrics
from seldon_core_tpu.contracts.payload import Feedback, SeldonError, SeldonMessage

logger = logging.getLogger(__name__)


class SeldonComponent:
    """Base class for graph components (models, routers, transformers, combiners)."""

    def __init__(self, **kwargs: Any):
        pass

    # -- lifecycle ------------------------------------------------------
    def load(self) -> None:
        """Load model artifacts; called once before serving."""

    # -- MODEL ----------------------------------------------------------
    def predict(
        self, X: np.ndarray, names: Sequence[str], meta: Optional[Dict] = None
    ) -> Union[np.ndarray, List, str, bytes]:
        raise NotImplementedError

    def predict_raw(self, msg: SeldonMessage) -> Union[SeldonMessage, Dict, np.ndarray, str, bytes]:
        raise NotImplementedError

    # -- TRANSFORMER ----------------------------------------------------
    def transform_input(
        self, X: np.ndarray, names: Sequence[str], meta: Optional[Dict] = None
    ) -> Union[np.ndarray, List, str, bytes]:
        raise NotImplementedError

    def transform_input_raw(self, msg: SeldonMessage) -> Union[SeldonMessage, Dict, np.ndarray, str, bytes]:
        raise NotImplementedError

    def transform_output(
        self, X: np.ndarray, names: Sequence[str], meta: Optional[Dict] = None
    ) -> Union[np.ndarray, List, str, bytes]:
        raise NotImplementedError

    def transform_output_raw(self, msg: SeldonMessage) -> Union[SeldonMessage, Dict, np.ndarray, str, bytes]:
        raise NotImplementedError

    # -- ROUTER ---------------------------------------------------------
    def route(self, X: np.ndarray, names: Sequence[str]) -> int:
        raise NotImplementedError

    def route_raw(self, msg: SeldonMessage) -> Union[SeldonMessage, Dict, int]:
        raise NotImplementedError

    # -- COMBINER -------------------------------------------------------
    def aggregate(
        self, Xs: Sequence[np.ndarray], names: Sequence[Sequence[str]]
    ) -> Union[np.ndarray, List, str, bytes]:
        raise NotImplementedError

    def aggregate_raw(self, msgs: Sequence[SeldonMessage]) -> Union[SeldonMessage, Dict, np.ndarray]:
        raise NotImplementedError

    # -- FEEDBACK -------------------------------------------------------
    def send_feedback(
        self,
        features: np.ndarray,
        feature_names: Sequence[str],
        reward: float,
        truth: Optional[np.ndarray],
        routing: Optional[int] = None,
    ) -> Optional[Union[np.ndarray, List]]:
        raise NotImplementedError

    def send_feedback_raw(self, feedback: Feedback) -> Union[SeldonMessage, Dict, None]:
        raise NotImplementedError

    # -- enrichment -----------------------------------------------------
    def tags(self) -> Dict[str, Any]:
        raise NotImplementedError

    def metrics(self) -> List[Dict[str, Any]]:
        raise NotImplementedError

    def feature_names(self) -> List[str]:
        raise NotImplementedError

    def class_names(self) -> List[str]:
        raise NotImplementedError

    # -- TPU-native hook ------------------------------------------------
    def jax_fn(self) -> Optional[Tuple[Callable[..., Any], Any]]:
        """Return ``(fn, params)`` where ``fn(params, x)`` is pure and jittable,
        or None. Enables whole-graph XLA fusion and mesh sharding."""
        return None


# ---------------------------------------------------------------------------
# client_* helpers: tolerant invocation with graceful fallbacks, the
# capability of `python/seldon_core/user_model.py:94-331`.
# ---------------------------------------------------------------------------

_IMPL_CACHE: Dict[Any, bool] = {}


def _has_impl(obj: Any, name: str) -> bool:
    """True if obj defines `name` itself (not the NotImplementedError base
    stub). Class-level answers are cached — this runs several times per
    request on the serving path, and the reflection chain costs more than
    the rest of the meta assembly. Instance-level overrides (obj.tags = fn)
    bypass the cache."""
    d = getattr(obj, "__dict__", None)
    if d is not None and name in d:
        return callable(d[name])
    cls = type(obj)
    key = (cls, name)
    hit = _IMPL_CACHE.get(key)
    if hit is None:
        meth = getattr(cls, name, None)
        if meth is None or not callable(meth):
            hit = False
        else:
            base = getattr(SeldonComponent, name, None)
            hit = not (base is not None and meth is base)
        _IMPL_CACHE[key] = hit
    return hit


def has_raw(obj: Any, name: str) -> bool:
    return _has_impl(obj, name + "_raw")


def client_custom_tags(component: Any) -> Dict[str, Any]:
    if _has_impl(component, "tags"):
        tags = component.tags()
        if tags is not None:
            if not isinstance(tags, dict):
                raise SeldonError("tags() must return a dict")
            return tags
    return {}


def client_custom_metrics(component: Any) -> List[Dict[str, Any]]:
    if _has_impl(component, "metrics"):
        metrics = component.metrics()
        if metrics is not None:
            if not validate_metrics(metrics):
                raise SeldonError(
                    "Bad metrics: must be a list of {key: str, type: COUNTER|GAUGE|TIMER, value: number}"
                )
            return list(metrics)
    return []


def client_feature_names(component: Any, original: Sequence[str]) -> List[str]:
    if _has_impl(component, "feature_names"):
        names = component.feature_names()
        if names is not None:
            return list(names)
    return list(original or [])


def client_class_names(component: Any, predictions: np.ndarray) -> List[str]:
    if _has_impl(component, "class_names"):
        names = component.class_names()
        if names is not None:
            return list(names)
    # Default "t:0..n" naming for 2-D outputs, as the reference does
    # (`user_model.py:94-119`).
    arr = np.asarray(predictions)
    if arr.ndim > 1:
        return [f"t:{i}" for i in range(arr.shape[1])]
    return []


def client_predict(component: Any, X: np.ndarray, names: Sequence[str], meta: Optional[Dict] = None):
    if _has_impl(component, "predict"):
        try:
            return component.predict(X, names, meta=meta)
        except TypeError:
            return component.predict(X, names)
    return []


def client_transform_input(component: Any, X: np.ndarray, names: Sequence[str], meta: Optional[Dict] = None):
    if _has_impl(component, "transform_input"):
        try:
            return component.transform_input(X, names, meta=meta)
        except TypeError:
            return component.transform_input(X, names)
    return X


def client_transform_output(component: Any, X: np.ndarray, names: Sequence[str], meta: Optional[Dict] = None):
    if _has_impl(component, "transform_output"):
        try:
            return component.transform_output(X, names, meta=meta)
        except TypeError:
            return component.transform_output(X, names)
    return X


def client_route(component: Any, X: np.ndarray, names: Sequence[str]) -> int:
    if _has_impl(component, "route"):
        return component.route(X, names)
    return -1


def client_aggregate(component: Any, Xs: Sequence[np.ndarray], names: Sequence[Sequence[str]]):
    if _has_impl(component, "aggregate"):
        return component.aggregate(Xs, names)
    raise SeldonError("Aggregate not defined on component")


def client_send_feedback(
    component: Any,
    features: np.ndarray,
    feature_names: Sequence[str],
    reward: float,
    truth: Optional[np.ndarray],
    routing: Optional[int],
):
    if _has_impl(component, "send_feedback"):
        return component.send_feedback(features, feature_names, reward, truth, routing=routing)
    return None
