"""Method dispatch: SeldonMessage in -> component call -> SeldonMessage out.

Capability of the reference's `python/seldon_core/seldon_methods.py:11-229`,
shared by REST, gRPC and the in-process graph engine (the reference runs this
only inside each microservice; here it is also the node-invocation layer of
the single-process engine). For each method: prefer the component's ``*_raw``
low-level hook, else extract the payload, call the high-level method, and
construct the response with the reference's encoding rules.
"""

from __future__ import annotations

import inspect
import os
from typing import Any, Awaitable, List, Optional, Sequence, Union

import numpy as np

from seldon_core_tpu.codec.response import construct_response, response_meta
from seldon_core_tpu.components.component import (
    client_aggregate,
    client_predict,
    client_route,
    client_send_feedback,
    client_transform_input,
    client_transform_output,
    has_raw,
)
from seldon_core_tpu.contracts.payload import (
    Feedback,
    Meta,
    SeldonError,
    SeldonMessage,
    SeldonMessageList,
)


def _coerce_raw(component: Any, result: Any, request: Optional[SeldonMessage], is_request: bool):
    """Normalize a *_raw return into a SeldonMessage. If the raw hook is a
    coroutine (e.g. a remote node), returns a coroutine the caller awaits."""
    if inspect.isawaitable(result):
        async def _await():
            return _coerce_raw(component, await result, request, is_request)

        return _await()
    if isinstance(result, SeldonMessage):
        return result
    if isinstance(result, dict):
        return SeldonMessage.from_dict(result)
    return construct_response(component, is_request, request, result)


def _respond(component: Any, is_request: bool, request, result):
    """construct_response that tolerates a *sync* component method returning
    an awaitable (async __call__ objects, sync defs delegating to async
    impls — the shapes iscoroutinefunction cannot see): the awaitable is
    awaited first, so it reaches the payload coercion as a value, and the
    caller gets an awaitable it already knows how to handle (every transport
    and the engine's _call await awaitable dispatch results)."""
    if inspect.isawaitable(result):
        async def _await():
            return construct_response(component, is_request, request, await result)

        return _await()
    return construct_response(component, is_request, request, result)


def predict(component: Any, request: SeldonMessage):
    """Returns a SeldonMessage — or, when the request joins a shared
    continuous batch from async code, an Awaitable[SeldonMessage] (every
    transport in this repo already handles awaitable results, matching the
    is_async component path)."""
    if has_raw(component, "predict"):
        return _coerce_raw(component, component.predict_raw(request), request, is_request=False)
    batched = _maybe_continuous_batch(component, request)
    if batched is not None:
        return batched
    payload = request.payload()
    result = client_predict(component, payload, request.names, meta=request.meta.to_dict())
    return _respond(component, False, request, result)


def _maybe_continuous_batch(component: Any, request: SeldonMessage):
    """Single-prompt LLM predicts join the component's shared continuous
    batch when it opted in (``continuous_batching`` slots > 0) — regardless
    of which transport reached this dispatch (component REST/gRPC, the graph
    engine, or the edge's ring fallback), concurrent clients then share one
    in-flight decode. The RESPONSE is byte-identical in shape to the
    unbatched path (generate()'s {"texts", "tokens"} dict through
    construct_response, meta included); per-request sampling params keep the
    private path so output never silently changes."""
    if int(getattr(component, "continuous_batching", 0) or 0) <= 0:
        return None  # a streaming-only 1-slot service must not capture /predict
    if request.which != "jsonData" or not isinstance(request.json_data, dict):
        return None
    body = request.json_data
    if "prompt" not in body or "prompts" in body \
            or "temperature" in body or "seed" in body:
        return None
    from seldon_core_tpu.runtime.batcher import get_batcher_service

    svc = get_batcher_service(component)
    if svc is None:
        return None

    # join the inbound trace: the transport's server span is active here
    # (rest.py / grpc_server.py opened it from the traceparent), so the
    # request's flight-recorder timeline roots under it instead of a
    # fresh 'internal' trace the caller's id can never find; the ingress
    # label inherits the span's name (predict / grpc:predict / ...)
    from seldon_core_tpu.tracing import current_trace_context, get_tracer

    trace = current_trace_context() if get_tracer().enabled else None
    info: dict = {}

    def to_msg(toks):
        # same shape + meta as the unbatched path: LLMServer.predict returns
        # {"texts": [...], "tokens": [[...]]} for jsonData prompts
        tokenizer = getattr(component, "_tokenizer", None)
        text = (tokenizer.decode(toks) if tokenizer is not None
                and isinstance(body["prompt"], str) else None)
        msg = construct_response(
            component, False, request, {"texts": [text], "tokens": [toks]})
        if info.get("truncated_prompt"):
            # truncation changes outputs — tell the CLIENT, not just the log
            msg.meta.tags["seldon.io/truncated-prompt"] = info["truncated_prompt"]
        return msg

    import asyncio

    # multi-tenant identity as jsonData fields (docs/multitenancy.md) —
    # the /predict surface carries no custom headers, so tenant / SLO
    # class / adapter ride the body here
    ident = dict(tenant=body.get("tenant"), slo_class=body.get("slo_class"),
                 adapter=body.get("adapter"))

    try:
        asyncio.get_running_loop()
    except RuntimeError:
        # sync transport (gRPC worker thread): block this thread only
        return to_msg(svc.submit_sync(body["prompt"], body.get("max_new_tokens"),
                                      info=info, trace=trace, **ident))

    async def run():
        # async transport (graph engine, REST app, ring handler): never block
        # the event loop while the shared batch decodes
        toks = await svc.submit(body["prompt"], body.get("max_new_tokens"),
                                info=info, trace=trace, **ident)
        return to_msg(toks)

    return run()


def transform_input(component: Any, request: SeldonMessage) -> Union[SeldonMessage, Awaitable[SeldonMessage]]:
    if has_raw(component, "transform_input"):
        return _coerce_raw(component, component.transform_input_raw(request), request, is_request=True)
    payload = request.payload()
    result = client_transform_input(component, payload, request.names, meta=request.meta.to_dict())
    return _respond(component, True, request, result)


def transform_output(component: Any, request: SeldonMessage) -> Union[SeldonMessage, Awaitable[SeldonMessage]]:
    if has_raw(component, "transform_output"):
        return _coerce_raw(component, component.transform_output_raw(request), request, is_request=False)
    payload = request.payload()
    result = client_transform_output(component, payload, request.names, meta=request.meta.to_dict())
    return _respond(component, False, request, result)


def route(component: Any, request: SeldonMessage) -> Union[SeldonMessage, Awaitable[SeldonMessage]]:
    """Returns a 1x1 ndarray-encoded branch index, as the reference does
    (`seldon_methods.py:159-189`); the index must be an int >= -1."""
    if has_raw(component, "route"):
        raw = component.route_raw(request)
        msg = _coerce_raw(component, raw, request, is_request=False)
        if inspect.isawaitable(msg):
            async def _await():
                out = await msg
                _validate_route_msg(out)
                return out

            return _await()
        _validate_route_msg(msg)
        return msg
    payload = request.payload()
    branch = client_route(component, payload, request.names)
    if inspect.isawaitable(branch):  # sync def returning an awaitable
        async def _await():
            return _route_response(component, request, await branch)

        return _await()
    return _route_response(component, request, branch)


def _route_response(component: Any, request: SeldonMessage, branch) -> SeldonMessage:
    if not isinstance(branch, int) or isinstance(branch, bool):
        raise SeldonError("Routing response must be an integer")
    if branch < -1:
        raise SeldonError(f"Routing response invalid: {branch} (must be >= -1)")
    msg = construct_response(component, False, request, np.array([[branch]]))
    if msg.data is not None:
        msg.data.encoding = "ndarray"
        msg.data.raw_ndarray = [[branch]]
    return msg


def _validate_route_msg(msg: SeldonMessage) -> None:
    arr = msg.payload()
    if isinstance(arr, np.ndarray):
        flat = arr.ravel()
        if flat.size != 1 or int(flat[0]) < -1:
            raise SeldonError(f"Routing response invalid: {flat.tolist()}")


def extract_route(msg: SeldonMessage) -> int:
    arr = msg.payload()
    if isinstance(arr, np.ndarray):
        flat = arr.ravel()
        if flat.size == 1:
            return int(flat[0])
    raise SeldonError("Routing response must contain a single integer")


def aggregate(component: Any, requests: SeldonMessageList) -> Union[SeldonMessage, Awaitable[SeldonMessage]]:
    if has_raw(component, "aggregate"):
        return _coerce_raw(component, component.aggregate_raw(requests.messages), None, is_request=False)
    arrays: List[np.ndarray] = []
    names: List[Sequence[str]] = []
    for m in requests.messages:
        arrays.append(m.payload())
        names.append(m.names)
    result = client_aggregate(component, arrays, names)
    first = requests.messages[0] if requests.messages else None
    return _respond(component, False, first, result)


def send_feedback(component: Any, feedback: Feedback, unit_id: Optional[str] = None) -> Union[SeldonMessage, Awaitable[SeldonMessage]]:
    """Deliver feedback. ``unit_id`` selects this unit's routing decision from
    the response meta (the reference reads env PREDICTIVE_UNIT_ID,
    `seldon_methods.py:52-90`)."""
    if has_raw(component, "send_feedback"):
        raw = component.send_feedback_raw(feedback)
        if raw is None:
            return SeldonMessage(meta=response_meta(component, None))
        return _coerce_raw(component, raw, feedback.request, is_request=False)
    # fall through to the high-level path below

    features: Optional[np.ndarray] = None
    feature_names: Sequence[str] = []
    if feedback.request is not None:
        features = feedback.request.payload()
        feature_names = feedback.request.names
    truth = feedback.truth.payload() if feedback.truth is not None else None

    routing: Optional[int] = None
    uid = unit_id if unit_id is not None else os.environ.get("PREDICTIVE_UNIT_ID", "")
    if feedback.response is not None and uid:
        routing = feedback.response.meta.routing.get(uid)

    result = client_send_feedback(component, features, feature_names, feedback.reward, truth, routing)
    if inspect.isawaitable(result):  # sync def returning an awaitable
        async def _await():
            value = await result
            if value is None:
                return SeldonMessage(meta=response_meta(component, None))
            return construct_response(component, False, feedback.request, value)

        return _await()
    if result is None:
        return SeldonMessage(meta=response_meta(component, None))
    return construct_response(component, False, feedback.request, result)
