"""Built-in graph units.

Parity with the reference engine's hardcoded implementations used for tests,
benchmarks and spec defaults (`engine/src/main/java/io/seldon/engine/
predictors/{SimpleModelUnit,SimpleRouterUnit,AverageCombinerUnit,
RandomABTestUnit}.java`) — except here they are JAX functions, so a graph of
built-ins fuses into a single XLA computation.
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from seldon_core_tpu.components.component import SeldonComponent
from seldon_core_tpu.components.metrics import create_counter, create_gauge, create_timer
from seldon_core_tpu.contracts.graph import UnitImplementation


class SimpleModel(SeldonComponent):
    """Constant stub model: returns [[0.1, 0.9, 0.5]] per row and sample
    metrics, echoes bytes/str payloads — the benchmark stub of
    `engine/.../SimpleModelUnit.java:33-64`."""

    values = (0.1, 0.9, 0.5)
    classes = ("class0", "class1", "class2")

    def predict(self, X, names: Sequence[str], meta: Optional[Dict] = None):
        if isinstance(X, (bytes, bytearray, str)) or X is None:
            return X
        # Host-side constant, like the reference's in-engine Java stub: this
        # unit benchmarks the orchestrator, so it must not pay a device round
        # trip per request. The jitted twin (jax_fn) serves whole-graph fusion.
        arr = np.asarray(X, dtype=np.float32)  # keep rejecting non-numeric payloads
        rows = arr.shape[0] if arr.ndim > 1 else 1
        return np.tile(np.asarray(self.values, dtype=np.float32), (rows, 1))

    def jax_fn(self):
        return self._fn, None

    @staticmethod
    def _fn(params: Any, x):
        import jax.numpy as jnp

        # row semantics must match the host path above: a 1-D payload is one
        # sample, not shape[0] samples
        rows = x.shape[0] if x.ndim >= 2 else 1
        out = jnp.tile(jnp.asarray(SimpleModel.values, dtype=jnp.float32), (rows, 1))
        return out

    def class_names(self) -> List[str]:
        return list(self.classes)

    def metrics(self):
        return [
            create_counter("mycounter", 1.0),
            create_gauge("mygauge", 100.0),
            create_timer("mytimer", 20.6),
        ]


class SimpleRouter(SeldonComponent):
    """Always route to branch 0 (`engine/.../SimpleRouterUnit.java`)."""

    def route(self, X, names: Sequence[str]) -> int:
        return 0


class RandomABTest(SeldonComponent):
    """Uniform-random branch choice (`engine/.../RandomABTestUnit.java`)."""

    def __init__(self, ratioA: float = 0.5, n_branches: int = 2, seed: Optional[int] = None, **kwargs):
        super().__init__(**kwargs)
        self.ratio_a = float(ratioA)
        self.n_branches = int(n_branches)
        self._rng = random.Random(seed)

    def route(self, X, names: Sequence[str]) -> int:
        if self.n_branches == 2:
            return 0 if self._rng.random() < self.ratio_a else 1
        return self._rng.randrange(self.n_branches)


class AverageCombiner(SeldonComponent):
    """Element-wise mean of child outputs (`engine/.../AverageCombinerUnit.java`
    + `PredictorUtils.java`), as a jitted stacked-mean."""

    def aggregate(self, Xs: Sequence[np.ndarray], names: Sequence[Sequence[str]]):
        if not Xs:
            raise ValueError("AverageCombiner requires at least one input")
        shapes = {np.asarray(x).shape for x in Xs}
        if len(shapes) != 1:
            raise ValueError(f"AverageCombiner inputs must share a shape, got {sorted(shapes)}")
        # host-side mean (tiny data, orchestrator-benchmark unit — see
        # SimpleModel.predict); the jitted twin serves whole-graph fusion
        return np.stack([np.asarray(x, dtype=np.float64) for x in Xs]).mean(axis=0)

    def jax_fn(self):
        return self._fn, None

    @staticmethod
    def _fn(params: Any, stacked):
        return stacked.mean(axis=0)


def make_builtin(implementation: UnitImplementation, parameters: Optional[Dict[str, Any]] = None) -> SeldonComponent:
    """Instantiate a built-in unit from a graph spec implementation."""
    parameters = parameters or {}
    if implementation == UnitImplementation.SIMPLE_MODEL:
        return SimpleModel()
    if implementation == UnitImplementation.SIMPLE_ROUTER:
        return SimpleRouter()
    if implementation == UnitImplementation.RANDOM_ABTEST:
        return RandomABTest(**parameters)
    if implementation == UnitImplementation.AVERAGE_COMBINER:
        return AverageCombiner()
    analytics = {
        UnitImplementation.EPSILON_GREEDY: "EpsilonGreedy",
        UnitImplementation.THOMPSON_SAMPLING: "ThompsonSampling",
        UnitImplementation.MAHALANOBIS_OD: "MahalanobisOutlierDetector",
        UnitImplementation.ISOLATION_FOREST_OD: "IsolationForestOutlierDetector",
        UnitImplementation.VAE_OD: "VAEOutlierDetector",
        UnitImplementation.SEQ2SEQ_OD: "Seq2SeqOutlierDetector",
    }
    if implementation in analytics:
        import seldon_core_tpu.analytics as _analytics

        return getattr(_analytics, analytics[implementation])(**parameters)
    raise ValueError(f"No in-process builtin for implementation {implementation}")
