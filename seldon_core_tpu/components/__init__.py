from seldon_core_tpu.components.component import SeldonComponent
from seldon_core_tpu.components.metrics import (
    create_counter,
    create_gauge,
    create_timer,
    validate_metrics,
)

__all__ = [
    "SeldonComponent",
    "create_counter",
    "create_gauge",
    "create_timer",
    "validate_metrics",
]
