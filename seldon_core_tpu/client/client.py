"""SeldonClient: one client for every serving path.

Capability of the reference's `python/seldon_core/seldon_client.py:104+`
(microservice-direct and gateway paths, REST + gRPC, all graph methods) minus
the legacy OAuth APIFE. Two endpoint kinds:

- ``engine``: the external API of a predictor (`/api/v0.1/predictions`,
  `/api/v0.1/feedback`; gRPC service ``Seldon``) — what a deployed graph
  exposes behind the gateway.
- ``microservice``: a single component's internal API (`/predict`,
  `/transform-input`, ...; gRPC services Model/Router/Transformer/Combiner) —
  what the engine calls per node.
- ``gateway``: the engine API through the cluster ingress — REST requests go
  to ``/seldon/<namespace>/<deployment>/api/v0.1/...`` (the Istio
  VirtualService prefix rendered by controlplane/render.py, matching the
  reference's Ambassador/Istio path, `seldon_client.py:513`), gRPC carries
  ``seldon``/``namespace`` metadata headers for the ingress to route on.

TLS: ``ssl=True`` switches REST to https (``ca_cert``/``client_cert``/
``client_key`` for verification and mutual TLS) and gRPC to a secure channel
built from the same PEMs; ``auth_token`` rides as a Bearer header / gRPC
authorization metadata (reference: `seldon_client.py:1137` channel and call
credentials).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from seldon_core_tpu.contracts.payload import (
    Feedback,
    SeldonMessage,
    SeldonMessageList,
)


@dataclasses.dataclass
class ClientResponse:
    success: bool
    msg: Optional[SeldonMessage]
    raw: Optional[Dict[str, Any]]
    error: Optional[str] = None

    @property
    def data(self) -> Optional[np.ndarray]:
        if self.msg is None or self.msg.data is None:
            return None
        return self.msg.data.to_numpy()


def _to_message(payload: Any, bin_data=None, str_data=None, json_data=None) -> SeldonMessage:
    if isinstance(payload, SeldonMessage):
        return payload
    if bin_data is not None:
        return SeldonMessage.from_bytes(bytes(bin_data))
    if str_data is not None:
        return SeldonMessage.from_str(str_data)
    if json_data is not None:
        return SeldonMessage.from_json_data(json_data)
    if payload is None:
        payload = np.array([[1.0]])
    return SeldonMessage.from_array(np.asarray(payload))


class SeldonClient:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 8000,
        transport: str = "rest",
        endpoint_kind: str = "engine",
        timeout_s: float = 10.0,
        names: Optional[Sequence[str]] = None,
        deployment_name: Optional[str] = None,
        namespace: str = "default",
        ssl: bool = False,
        ca_cert: Optional[str] = None,
        client_cert: Optional[str] = None,
        client_key: Optional[str] = None,
        auth_token: Optional[str] = None,
    ):
        if transport not in ("rest", "grpc"):
            raise ValueError(f"transport must be rest|grpc, got {transport}")
        if endpoint_kind not in ("engine", "microservice", "gateway"):
            raise ValueError(
                f"endpoint_kind must be engine|microservice|gateway, got {endpoint_kind}"
            )
        if endpoint_kind == "gateway" and not deployment_name:
            raise ValueError("gateway endpoint needs deployment_name")
        self.host = host
        self.port = int(port)
        self.transport = transport
        self.endpoint_kind = endpoint_kind
        self.timeout_s = float(timeout_s)
        self.names = list(names or [])
        self.deployment_name = deployment_name
        self.namespace = namespace
        self.ssl = bool(ssl)
        self.ca_cert = ca_cert
        self.client_cert = client_cert
        self.client_key = client_key
        self.auth_token = auth_token
        self._channel_credentials = None  # built once on first gRPC call

    # ------------------------------------------------------------- REST
    def _rest_url(self, path: str) -> str:
        scheme = "https" if self.ssl else "http"
        prefix = ""
        if self.endpoint_kind == "gateway":
            prefix = f"/seldon/{self.namespace}/{self.deployment_name}"
        return f"{scheme}://{self.host}:{self.port}{prefix}{path}"

    def _rest_call(self, path: str, body: Dict[str, Any]) -> ClientResponse:
        import requests

        kwargs: Dict[str, Any] = {"json": body, "timeout": self.timeout_s}
        if self.ssl:
            kwargs["verify"] = self.ca_cert if self.ca_cert else True
            if self.client_cert:
                kwargs["cert"] = (self.client_cert, self.client_key)
        if self.auth_token:
            kwargs["headers"] = {"Authorization": f"Bearer {self.auth_token}"}
        try:
            r = requests.post(self._rest_url(path), **kwargs)
            raw = r.json()
        except Exception as e:  # connection/JSON errors
            return ClientResponse(False, None, None, error=str(e))
        if r.status_code != 200:
            return ClientResponse(False, None, raw, error=json.dumps(raw))
        return ClientResponse(True, SeldonMessage.from_dict(raw), raw)

    # ------------------------------------------------------------- gRPC
    def _grpc_metadata(self) -> Optional[List]:
        md = []
        if self.endpoint_kind == "gateway":
            # ingress routing headers (reference: grpc_predict_gateway's
            # seldon/namespace metadata, seldon_client.py:1137+)
            md += [("seldon", self.deployment_name), ("namespace", self.namespace)]
        if self.auth_token:
            md.append(("authorization", f"Bearer {self.auth_token}"))
        return md or None

    def _grpc_credentials(self):
        if not self.ssl:
            return None
        if self._channel_credentials is None:
            from seldon_core_tpu.transport.grpc_client import make_channel_credentials

            self._channel_credentials = make_channel_credentials(
                self.ca_cert, self.client_cert, self.client_key
            )
        return self._channel_credentials

    def _grpc_call(self, method: str, msg: Any, service: str) -> ClientResponse:
        from seldon_core_tpu.transport import grpc_client

        try:
            out = grpc_client.call_sync(
                f"{self.host}:{self.port}", method, msg, service=service,
                timeout_s=self.timeout_s, credentials=self._grpc_credentials(),
                metadata=self._grpc_metadata(),
            )
        except Exception as e:
            return ClientResponse(False, None, None, error=str(e))
        return ClientResponse(True, out, out.to_dict())

    # ------------------------------------------------------------ methods
    def predict(
        self,
        data: Any = None,
        names: Optional[Sequence[str]] = None,
        bin_data=None,
        str_data=None,
        json_data=None,
    ) -> ClientResponse:
        msg = _to_message(data, bin_data, str_data, json_data)
        if (names or self.names) and msg.data is not None:
            msg.data.names = list(names or self.names)
        if self.transport == "rest":
            path = "/predict" if self.endpoint_kind == "microservice" else "/api/v0.1/predictions"
            return self._rest_call(path, msg.to_dict())
        service = "Model" if self.endpoint_kind == "microservice" else "Seldon"
        return self._grpc_call("Predict", msg, service)

    def feedback(
        self,
        request: Optional[Union[SeldonMessage, Dict]] = None,
        response: Optional[Union[SeldonMessage, Dict]] = None,
        reward: float = 0.0,
        truth: Any = None,
    ) -> ClientResponse:
        fb = Feedback(
            request=_as_msg(request),
            response=_as_msg(response),
            reward=float(reward),
            truth=SeldonMessage.from_array(np.asarray(truth)) if truth is not None else None,
        )
        if self.transport == "rest":
            path = "/send-feedback" if self.endpoint_kind == "microservice" else "/api/v0.1/feedback"
            return self._rest_call(path, fb.to_dict())
        service = "Model" if self.endpoint_kind == "microservice" else "Seldon"
        return self._grpc_call("SendFeedback", fb, service)

    # microservice-only graph methods
    def transform_input(self, data: Any, names: Optional[Sequence[str]] = None) -> ClientResponse:
        return self._unit_call("TransformInput", "/transform-input", data, names, "Transformer")

    def transform_output(self, data: Any, names: Optional[Sequence[str]] = None) -> ClientResponse:
        return self._unit_call(
            "TransformOutput", "/transform-output", data, names, "OutputTransformer"
        )

    def route(self, data: Any, names: Optional[Sequence[str]] = None) -> ClientResponse:
        return self._unit_call("Route", "/route", data, names, "Router")

    def aggregate(self, datas: Sequence[Any]) -> ClientResponse:
        msgs = SeldonMessageList(messages=[_to_message(d) for d in datas])
        if self.transport == "rest":
            return self._rest_call("/aggregate", msgs.to_dict())
        return self._grpc_call("Aggregate", msgs, "Combiner")

    def _unit_call(self, method, path, data, names, service) -> ClientResponse:
        if self.endpoint_kind != "microservice":
            raise ValueError(f"{method} is a microservice-level call")
        msg = _to_message(data)
        if (names or self.names) and msg.data is not None:
            msg.data.names = list(names or self.names)
        if self.transport == "rest":
            return self._rest_call(path, msg.to_dict())
        return self._grpc_call(method, msg, service)


def _as_msg(x: Optional[Union[SeldonMessage, Dict]]) -> Optional[SeldonMessage]:
    if x is None:
        return None
    if isinstance(x, SeldonMessage):
        return x
    return SeldonMessage.from_dict(x)
