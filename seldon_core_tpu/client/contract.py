"""Contract-based payload fuzzing.

Capability of the reference's `python/seldon_core/microservice_tester.py:
83-155` and `serving_test_gen.py:61`: a ``contract.json`` describes each
feature (continuous range or categorical values, dtype, shape); the tester
samples random conforming batches, fires them at an endpoint, and checks the
response against the target schema.

Contract shape::

    {"features": [{"name": "f1", "ftype": "continuous", "dtype": "FLOAT",
                   "range": [0, 1], "shape": [2]},   # optional shape => repeat
                  {"name": "c", "ftype": "categorical", "values": ["a", "b"]}],
     "targets":  [...same...]}

``range`` endpoints may be the string "inf"/"-inf" for unbounded sides.
"""

from __future__ import annotations

import json
import math
from typing import Any, Dict, List, Optional

import numpy as np


class ContractError(Exception):
    pass


def load_contract(path: str) -> Dict[str, Any]:
    with open(path) as f:
        contract = json.load(f)
    if "features" not in contract:
        raise ContractError("contract.json must have a 'features' list")
    return unfold_contract(contract)


def unfold_contract(contract: Dict[str, Any]) -> Dict[str, Any]:
    """Expand features carrying a ``shape`` into scalar features f:0..n, as
    the reference does (`microservice_tester.py:112-154`)."""
    out = {"features": [], "targets": []}
    for field in ("features", "targets"):
        for feature in contract.get(field, []):
            shape = feature.get("shape")
            n = int(np.prod(shape)) if shape else 1
            if n == 1:
                out[field].append(dict(feature))
            else:
                for i in range(n):
                    f = dict(feature)
                    f.pop("shape", None)
                    f["name"] = f"{feature.get('name', 'f')}:{i}"
                    out[field].append(f)
    return out


def _gen_continuous(rng: np.random.Generator, f_range, n: int) -> np.ndarray:
    lo, hi = (f_range or ["-inf", "inf"])[:2]
    lo_inf = lo in ("inf", "-inf") or (isinstance(lo, float) and math.isinf(lo))
    hi_inf = hi in ("inf", "-inf") or (isinstance(hi, float) and math.isinf(hi))
    if lo_inf and hi_inf:
        return rng.normal(size=n)
    if lo_inf:
        return float(hi) - rng.lognormal(size=n)
    if hi_inf:
        return float(lo) + rng.lognormal(size=n)
    return rng.uniform(float(lo), float(hi), size=n)


def generate_batch(
    contract: Dict[str, Any],
    n: int,
    field: str = "features",
    seed: Optional[int] = None,
) -> np.ndarray:
    """Sample an (n, n_features) batch conforming to the contract. Columns
    with categorical values produce an object array, matching the reference's
    mixed-type behavior."""
    rng = np.random.default_rng(seed)
    contract = unfold_contract(contract)
    cols: List[np.ndarray] = []
    categorical = False
    for feature in contract[field]:
        ftype = feature.get("ftype", "continuous")
        if ftype == "continuous":
            col = _gen_continuous(rng, feature.get("range"), n)
            if feature.get("dtype") == "INT":
                col = np.floor(col + 0.5)
            cols.append(col)
        elif ftype == "categorical":
            values = feature.get("values")
            if not values:
                raise ContractError(f"categorical feature {feature.get('name')} needs 'values'")
            cols.append(np.asarray(values)[rng.integers(len(values), size=n)])
            categorical = True
        else:
            raise ContractError(f"unknown ftype {ftype!r}")
    if not cols:
        raise ContractError(f"contract field {field!r} is empty")
    dtype = object if categorical else np.float64
    return np.stack([c.astype(dtype) for c in cols], axis=1)


def feature_names(contract: Dict[str, Any], field: str = "features") -> List[str]:
    return [f.get("name", f"f{i}") for i, f in enumerate(unfold_contract(contract)[field])]


def validate_response(contract: Dict[str, Any], response: np.ndarray) -> List[str]:
    """Check a response batch against the target schema: column count, ranges,
    categorical membership. Returns a list of violation strings (empty = ok)."""
    contract = unfold_contract(contract)
    targets = contract.get("targets", [])
    problems: List[str] = []
    arr = np.atleast_2d(np.asarray(response))
    if not targets:
        return problems
    if arr.shape[1] != len(targets):
        return [f"expected {len(targets)} target columns, got {arr.shape[1]}"]
    for j, target in enumerate(targets):
        col = arr[:, j]
        name = target.get("name", f"t{j}")
        if target.get("ftype", "continuous") == "categorical":
            allowed = set(map(str, target.get("values", [])))
            bad = [v for v in col if str(v) not in allowed]
            if bad:
                problems.append(f"{name}: values {bad[:3]} outside {sorted(allowed)}")
            continue
        f_range = target.get("range")
        if not f_range:
            continue
        lo, hi = f_range[:2]
        vals = col.astype(np.float64)
        if lo not in ("inf", "-inf") and np.any(vals < float(lo)):
            problems.append(f"{name}: value below range min {lo}")
        if hi not in ("inf", "-inf") and np.any(vals > float(hi)):
            problems.append(f"{name}: value above range max {hi}")
    return problems


def contract_from_dataframe(df, n_categorical_threshold: int = 20) -> Dict[str, Any]:
    """Build a contract from a pandas DataFrame (capability of
    `serving_test_gen.py:61`): low-cardinality object/int columns become
    categorical, numeric columns become continuous with observed ranges."""
    features = []
    for col in df.columns:
        s = df[col]
        numeric = s.dtype.kind in "biufc"
        if not numeric or (s.dtype.kind in "iu" and s.nunique() <= n_categorical_threshold):
            features.append(
                {
                    "name": str(col),
                    "ftype": "categorical",
                    "dtype": "INT" if numeric else "STRING",
                    "values": [str(v) for v in sorted(s.unique(), key=str)],
                }
            )
        else:
            features.append(
                {
                    "name": str(col),
                    "ftype": "continuous",
                    "dtype": "INT" if s.dtype.kind in "iu" else "FLOAT",
                    "range": [float(s.min()), float(s.max())],
                }
            )
    return {"features": features, "targets": []}
