"""Contract testers: fire random conforming payloads at a live endpoint and
validate the round trip.

Capability of the reference's CLIs `seldon-core-tester` (microservice-direct,
`microservice_tester.py`) and `seldon-core-api-tester` (engine/gateway,
`api_tester.py`). Exposed as ``python -m seldon_core_tpu.transport.cli
tester|api-tester`` subcommands.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
from typing import Optional

import numpy as np

from seldon_core_tpu.client.client import SeldonClient
from seldon_core_tpu.client.contract import (
    feature_names,
    generate_batch,
    load_contract,
    validate_response,
)

logger = logging.getLogger(__name__)


def run_contract_test(
    contract_path: str,
    host: str,
    port: int,
    n_requests: int = 1,
    batch_size: int = 1,
    grpc: bool = False,
    endpoint_kind: str = "microservice",
    method: str = "predict",
    seed: Optional[int] = None,
    show: bool = False,
) -> int:
    """Returns the number of failed requests (0 = success)."""
    contract = load_contract(contract_path)
    client = SeldonClient(
        host=host,
        port=port,
        transport="grpc" if grpc else "rest",
        endpoint_kind=endpoint_kind,
        names=feature_names(contract),
    )
    failures = 0
    for i in range(n_requests):
        batch = generate_batch(contract, batch_size, seed=None if seed is None else seed + i)
        if batch.dtype == object:
            payload = batch.tolist()  # mixed categorical -> ndarray JSON payload
        else:
            payload = batch
        if method == "predict":
            resp = client.predict(payload)
        elif method == "send-feedback":
            request_msg = {"data": {"ndarray": batch.tolist()}}
            resp = client.feedback(request=request_msg, reward=1.0)
        else:
            raise ValueError(f"unknown method {method}")
        ok = resp.success
        problems = []
        if ok and method == "predict" and resp.data is not None:
            problems = validate_response(contract, resp.data)
            ok = not problems
        if show or not ok:
            print(f"[{i}] success={resp.success} problems={problems} error={resp.error}")
            if resp.raw is not None:
                print(json.dumps(resp.raw)[:2000])
        failures += 0 if ok else 1
    print(f"{n_requests - failures}/{n_requests} requests passed")
    return failures


def add_tester_args(p: argparse.ArgumentParser, endpoint_kind: str) -> None:
    p.add_argument("contract", help="path to contract.json")
    p.add_argument("host")
    p.add_argument("port", type=int)
    p.add_argument("-n", "--n-requests", type=int, default=1)
    p.add_argument("-b", "--batch-size", type=int, default=1)
    p.add_argument("--grpc", action="store_true")
    p.add_argument("--endpoint", default="predict", choices=["predict", "send-feedback"])
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("-p", "--prnt", action="store_true", help="print every request/response")
    p.set_defaults(_endpoint_kind=endpoint_kind)


def tester_main(args: argparse.Namespace) -> None:
    failures = run_contract_test(
        args.contract,
        args.host,
        args.port,
        n_requests=args.n_requests,
        batch_size=args.batch_size,
        grpc=args.grpc,
        endpoint_kind=args._endpoint_kind,
        method=args.endpoint,
        seed=args.seed,
        show=args.prnt,
    )
    if failures:
        sys.exit(1)
