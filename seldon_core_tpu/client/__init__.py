"""Client SDK + contract-based test tooling (capability of the reference's
`python/seldon_core/{seldon_client.py,microservice_tester.py,api_tester.py,
serving_test_gen.py}`)."""

from seldon_core_tpu.client.client import ClientResponse, SeldonClient
from seldon_core_tpu.client.contract import (
    generate_batch,
    load_contract,
    unfold_contract,
    validate_response,
)

__all__ = [
    "SeldonClient",
    "ClientResponse",
    "generate_batch",
    "load_contract",
    "unfold_contract",
    "validate_response",
]
