"""Inference-graph spec: the framework's `SeldonDeployment` equivalent.

Parses the same JSON shape as the reference CRD (`proto/seldon_deployment.proto:11-161`):
a deployment has predictors; each predictor has a recursive ``graph`` of
``PredictiveUnit`` nodes with type (MODEL/ROUTER/COMBINER/TRANSFORMER/
OUTPUT_TRANSFORMER), optional built-in implementation, typed parameters,
endpoint (for remote nodes) and modelUri (for prepackaged servers).

TPU-first difference: a unit with no ``endpoint`` is an *in-process* component
(a Python/JAX object), not a microservice; endpoints exist only for genuinely
external nodes. The whole graph of in-process units runs in one engine process
(see seldon_core_tpu.runtime.engine).
"""

from __future__ import annotations

import base64
import json
import os
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, List, Optional

from seldon_core_tpu.contracts.parameters import Parameter
from seldon_core_tpu.contracts.payload import SeldonError


class UnitType(str, Enum):
    """`proto/seldon_deployment.proto` PredictiveUnitType."""

    UNKNOWN_TYPE = "UNKNOWN_TYPE"
    ROUTER = "ROUTER"
    COMBINER = "COMBINER"
    MODEL = "MODEL"
    TRANSFORMER = "TRANSFORMER"
    OUTPUT_TRANSFORMER = "OUTPUT_TRANSFORMER"


class UnitImplementation(str, Enum):
    """Built-in implementations (`proto/seldon_deployment.proto:102-113`).

    The *_SERVER values select prepackaged servers (seldon_core_tpu.servers);
    JAX_SERVER is this framework's native addition (BASELINE.json north star).
    """

    UNKNOWN_IMPLEMENTATION = "UNKNOWN_IMPLEMENTATION"
    SIMPLE_MODEL = "SIMPLE_MODEL"
    SIMPLE_ROUTER = "SIMPLE_ROUTER"
    RANDOM_ABTEST = "RANDOM_ABTEST"
    AVERAGE_COMBINER = "AVERAGE_COMBINER"
    SKLEARN_SERVER = "SKLEARN_SERVER"
    XGBOOST_SERVER = "XGBOOST_SERVER"
    TENSORFLOW_SERVER = "TENSORFLOW_SERVER"
    MLFLOW_SERVER = "MLFLOW_SERVER"
    JAX_SERVER = "JAX_SERVER"
    # Analytics units the reference ships as standalone container images
    # (`components/routers/`, `components/outlier-detection/`); here they are
    # in-process implementations selectable straight from the graph spec.
    EPSILON_GREEDY = "EPSILON_GREEDY"
    THOMPSON_SAMPLING = "THOMPSON_SAMPLING"
    MAHALANOBIS_OD = "MAHALANOBIS_OD"
    ISOLATION_FOREST_OD = "ISOLATION_FOREST_OD"
    VAE_OD = "VAE_OD"
    SEQ2SEQ_OD = "SEQ2SEQ_OD"


class UnitMethod(str, Enum):
    TRANSFORM_INPUT = "TRANSFORM_INPUT"
    TRANSFORM_OUTPUT = "TRANSFORM_OUTPUT"
    ROUTE = "ROUTE"
    AGGREGATE = "AGGREGATE"
    SEND_FEEDBACK = "SEND_FEEDBACK"


class EndpointType(str, Enum):
    REST = "REST"
    GRPC = "GRPC"


@dataclass(slots=True)
class Endpoint:
    """Remote-node endpoint (`proto/seldon_deployment.proto:135-145`)."""

    service_host: str = ""
    service_port: int = 0
    type: str = EndpointType.REST.value

    def to_dict(self) -> Dict[str, Any]:
        return {
            "service_host": self.service_host,
            "service_port": self.service_port,
            "type": self.type,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Endpoint":
        return cls(
            service_host=d.get("service_host", d.get("serviceHost", "")) or "",
            service_port=int(d.get("service_port", d.get("servicePort", 0)) or 0),
            type=d.get("type", EndpointType.REST.value) or EndpointType.REST.value,
        )


# Default methods per unit type, mirroring the reference's type->method
# dispatch table (`engine/.../PredictorConfigBean.java:30-107`).
DEFAULT_METHODS: Dict[UnitType, List[UnitMethod]] = {
    UnitType.MODEL: [UnitMethod.TRANSFORM_INPUT, UnitMethod.SEND_FEEDBACK],
    UnitType.ROUTER: [UnitMethod.ROUTE, UnitMethod.SEND_FEEDBACK],
    UnitType.COMBINER: [UnitMethod.AGGREGATE],
    UnitType.TRANSFORMER: [UnitMethod.TRANSFORM_INPUT],
    UnitType.OUTPUT_TRANSFORMER: [UnitMethod.TRANSFORM_OUTPUT],
}


@dataclass
class PredictiveUnit:
    """One graph node (`proto/seldon_deployment.proto:87-133`)."""

    name: str
    children: List["PredictiveUnit"] = field(default_factory=list)
    type: Optional[UnitType] = None
    implementation: Optional[UnitImplementation] = None
    methods: Optional[List[UnitMethod]] = None
    endpoint: Optional[Endpoint] = None
    parameters: List[Parameter] = field(default_factory=list)
    model_uri: str = ""
    service_account_name: str = ""
    env_secret_ref_name: str = ""

    def resolved_methods(self) -> List[UnitMethod]:
        """Methods this unit participates in: explicit list wins, else by type."""
        if self.methods is not None:
            return self.methods
        if self.type is not None:
            return DEFAULT_METHODS.get(self.type, [])
        return []

    def parameters_dict(self) -> Dict[str, Any]:
        return {p.name: p.typed_value() for p in self.parameters}

    def walk(self):
        """Yield this node and all descendants, pre-order."""
        yield self
        for c in self.children:
            yield from c.walk()

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"name": self.name}
        if self.type is not None:
            d["type"] = self.type.value
        if self.implementation is not None:
            d["implementation"] = self.implementation.value
        if self.methods is not None:
            d["methods"] = [m.value for m in self.methods]
        if self.endpoint is not None:
            d["endpoint"] = self.endpoint.to_dict()
        if self.parameters:
            d["parameters"] = [p.to_dict() for p in self.parameters]
        if self.model_uri:
            d["modelUri"] = self.model_uri
        if self.service_account_name:
            d["serviceAccountName"] = self.service_account_name
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PredictiveUnit":
        if "name" not in d:
            raise SeldonError("PredictiveUnit requires a name", reason="BAD_GRAPH")
        try:
            utype = UnitType(d["type"]) if "type" in d else None
        except ValueError:
            raise SeldonError(f"Unknown unit type: {d['type']}", reason="BAD_GRAPH")
        try:
            impl = UnitImplementation(d["implementation"]) if "implementation" in d else None
        except ValueError:
            raise SeldonError(f"Unknown implementation: {d['implementation']}", reason="BAD_GRAPH")
        methods = None
        if "methods" in d:
            methods = [UnitMethod(m) for m in d["methods"]]
        return cls(
            name=d["name"],
            children=[cls.from_dict(c) for c in d.get("children", []) or []],
            type=utype,
            implementation=impl,
            methods=methods,
            endpoint=Endpoint.from_dict(d["endpoint"]) if "endpoint" in d else None,
            parameters=[Parameter.from_dict(p) for p in d.get("parameters", []) or []],
            model_uri=d.get("modelUri", "") or "",
            service_account_name=d.get("serviceAccountName", "") or "",
            env_secret_ref_name=d.get("envSecretRefName", "") or "",
        )


@dataclass
class PredictorSpec:
    """One predictor: a graph + replica/traffic config
    (`proto/seldon_deployment.proto:47-85`)."""

    name: str
    graph: PredictiveUnit
    replicas: int = 1
    traffic: int = 0
    annotations: Dict[str, str] = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)
    shadow: bool = False
    component_specs: List[Dict[str, Any]] = field(default_factory=list)
    svc_orch_spec: Dict[str, Any] = field(default_factory=dict)
    # `SeldonHpaSpec` (proto/seldon_deployment.proto:72-76):
    # {minReplicas, maxReplicas, metrics: [...]}
    hpa_spec: Dict[str, Any] = field(default_factory=dict)
    # `Explainer` (proto/seldon_deployment.proto:45-51):
    # {type, modelUri, serviceAccountName, envSecretRefName, containerSpec}
    explainer: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "name": self.name,
            "graph": self.graph.to_dict(),
            "replicas": self.replicas,
        }
        if self.traffic:
            d["traffic"] = self.traffic
        if self.annotations:
            d["annotations"] = dict(self.annotations)
        if self.labels:
            d["labels"] = dict(self.labels)
        if self.shadow:
            d["shadow"] = True
        if self.component_specs:
            d["componentSpecs"] = self.component_specs
        if self.svc_orch_spec:
            d["svcOrchSpec"] = self.svc_orch_spec
        if self.hpa_spec:
            d["hpaSpec"] = self.hpa_spec
        if self.explainer:
            d["explainer"] = self.explainer
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PredictorSpec":
        if "graph" not in d:
            raise SeldonError("PredictorSpec requires a graph", reason="BAD_GRAPH")
        return cls(
            name=d.get("name", "default"),
            graph=PredictiveUnit.from_dict(d["graph"]),
            replicas=int(d.get("replicas", 1) or 1),
            traffic=int(d.get("traffic", 0) or 0),
            annotations=dict(d.get("annotations", {}) or {}),
            labels=dict(d.get("labels", {}) or {}),
            shadow=bool(d.get("shadow", False)),
            component_specs=list(d.get("componentSpecs", []) or []),
            svc_orch_spec=dict(d.get("svcOrchSpec", {}) or {}),
            hpa_spec=dict(d.get("hpaSpec", {}) or {}),
            explainer=dict(d.get("explainer", {}) or {}),
        )


@dataclass
class SeldonDeploymentSpec:
    """Whole-deployment spec (CRD `.spec`), `proto/seldon_deployment.proto:25-45`."""

    name: str
    predictors: List[PredictorSpec] = field(default_factory=list)
    annotations: Dict[str, str] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"name": self.name, "predictors": [p.to_dict() for p in self.predictors]}
        if self.annotations:
            d["annotations"] = dict(self.annotations)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SeldonDeploymentSpec":
        # Accept either a bare spec or a full CR ({"kind": "SeldonDeployment",
        # "metadata": ..., "spec": ...}).
        if d.get("kind") == "SeldonDeployment" or "spec" in d:
            name = d.get("metadata", {}).get("name", d.get("spec", {}).get("name", "seldon"))
            spec = d.get("spec", {})
        else:
            name = d.get("name", "seldon")
            spec = d
        return cls(
            name=name,
            predictors=[PredictorSpec.from_dict(p) for p in spec.get("predictors", []) or []],
            annotations=dict(spec.get("annotations", {}) or {}),
        )


def load_predictor_spec_from_env(env: Optional[Dict[str, str]] = None) -> Optional[PredictorSpec]:
    """Load a PredictorSpec the way the reference engine boots: base64 JSON in
    env ``ENGINE_PREDICTOR``, falling back to a ``./deploymentdef.json`` file
    (`engine/.../EnginePredictor.java:58-108`)."""
    env = env if env is not None else dict(os.environ)
    raw = env.get("ENGINE_PREDICTOR", "")
    if raw:
        decoded = base64.b64decode(raw).decode("utf-8")
        return PredictorSpec.from_dict(json.loads(decoded))
    path = env.get("ENGINE_PREDICTOR_FILE", "./deploymentdef.json")
    if os.path.exists(path):
        with open(path) as f:
            return PredictorSpec.from_dict(json.load(f))
    return None
