"""Payload schema: the framework's equivalent of `SeldonMessage`.

Wire-compatible with the reference proto-JSON (`proto/prediction.proto:14-91`):

    {"data": {"names": [...], "tensor": {"shape": [...], "values": [...]}}}
    {"data": {"names": [...], "ndarray": [[...], ...]}}
    {"binData": "<base64>"} | {"strData": "..."} | {"jsonData": <any>}
    meta: {"puid", "tags", "routing", "requestPath", "metrics"}

Design difference from the reference: the in-memory representation is *not* a
protobuf. `DefaultData.array` holds a live numpy or JAX array so that inside a
predictor graph tensors stay as device buffers — JSON (or proto) encode/decode
happens once at the process edge, not per graph node (the reference pays the
ndarray<->proto codec on every hop, `python/seldon_core/utils.py:147-278`).
"""

from __future__ import annotations

import base64
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

ArrayLike = Any  # np.ndarray or jax.Array; kept loose to avoid importing jax here.


class SeldonError(Exception):
    """Framework error carrying an HTTP-ish status code and structured payload.

    Equivalent of the reference's ``SeldonMicroserviceException``
    (`python/seldon_core/flask_utils.py:67-85`).
    """

    status_code = 400

    def __init__(self, message: str, status_code: Optional[int] = None, reason: str = "MICROSERVICE_BAD_DATA"):
        super().__init__(message)
        self.message = message
        if status_code is not None:
            self.status_code = status_code
        self.reason = reason

    def to_status(self) -> "Status":
        return Status(code=self.status_code, info=self.message, reason=self.reason, status="FAILURE")


class MetricType(str, Enum):
    COUNTER = "COUNTER"
    GAUGE = "GAUGE"
    TIMER = "TIMER"


@dataclass(slots=True)
class Metric:
    """In-band custom metric (`proto/prediction.proto:48-58`)."""

    key: str
    type: str = MetricType.COUNTER.value
    value: float = 0.0
    tags: Dict[str, str] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"key": self.key, "type": self.type, "value": self.value}
        if self.tags:
            d["tags"] = dict(self.tags)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Metric":
        return cls(
            key=d.get("key", ""),
            type=d.get("type", MetricType.COUNTER.value) or MetricType.COUNTER.value,
            value=float(d.get("value", 0.0)),
            tags=dict(d.get("tags", {}) or {}),
        )


@dataclass(slots=True)
class Meta:
    """Request/response metadata (`proto/prediction.proto:40-46`)."""

    puid: str = ""
    tags: Dict[str, Any] = field(default_factory=dict)
    routing: Dict[str, int] = field(default_factory=dict)
    request_path: Dict[str, str] = field(default_factory=dict)
    metrics: List[Metric] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {}
        if self.puid:
            d["puid"] = self.puid
        if self.tags:
            d["tags"] = dict(self.tags)
        if self.routing:
            d["routing"] = dict(self.routing)
        if self.request_path:
            d["requestPath"] = dict(self.request_path)
        if self.metrics:
            d["metrics"] = [m.to_dict() for m in self.metrics]
        return d

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "Meta":
        d = d or {}
        return cls(
            puid=d.get("puid", "") or "",
            tags=dict(d.get("tags", {}) or {}),
            routing={k: int(v) for k, v in (d.get("routing", {}) or {}).items()},
            request_path=dict(d.get("requestPath", {}) or {}),
            metrics=[Metric.from_dict(m) for m in (d.get("metrics", []) or [])],
        )

    def copy(self) -> "Meta":
        return Meta(
            puid=self.puid,
            tags=dict(self.tags),
            routing=dict(self.routing),
            request_path=dict(self.request_path),
            metrics=list(self.metrics),
        )


@dataclass(slots=True)
class Status:
    """Outcome status (`proto/prediction.proto:64-75`)."""

    code: int = 200
    info: str = ""
    reason: str = ""
    status: str = "SUCCESS"  # SUCCESS | FAILURE

    def to_dict(self) -> Dict[str, Any]:
        return {"code": self.code, "info": self.info, "reason": self.reason, "status": self.status}

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "Status":
        d = d or {}
        return cls(
            code=int(d.get("code", 200)),
            info=d.get("info", ""),
            reason=d.get("reason", ""),
            status=d.get("status", "SUCCESS") or "SUCCESS",
        )


# DefaultData encodings on the wire.
ENC_TENSOR = "tensor"
ENC_NDARRAY = "ndarray"
ENC_TFTENSOR = "tftensor"


@dataclass(slots=True)
class DefaultData:
    """Named tensor payload (`proto/prediction.proto:26-38`).

    ``array`` is the live array (numpy or jax.Array). ``encoding`` remembers
    which wire form the data arrived in (tensor | ndarray | tftensor) so
    responses can mirror the request encoding, matching the reference's
    construct-response rules (`python/seldon_core/utils.py:443-461`).
    """

    names: List[str] = field(default_factory=list)
    array: Optional[ArrayLike] = None
    encoding: str = ENC_TENSOR
    # ndarray payloads may hold non-numeric nested lists; keep the raw form.
    raw_ndarray: Optional[List[Any]] = None

    def to_numpy(self) -> np.ndarray:
        if self.array is not None:
            return np.asarray(self.array)
        return np.asarray(self.raw_ndarray, dtype=object)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {}
        if self.names:
            d["names"] = list(self.names)
        if self.encoding == ENC_TENSOR:
            arr = np.asarray(self.array)
            d["tensor"] = {"shape": list(arr.shape), "values": arr.ravel().tolist()}
        elif self.encoding == ENC_NDARRAY:
            if self.raw_ndarray is not None and self.array is None:
                d["ndarray"] = self.raw_ndarray
            else:
                d["ndarray"] = np.asarray(self.array).tolist()
        else:
            raise SeldonError(f"Unsupported DefaultData encoding for JSON: {self.encoding}")
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DefaultData":
        names = list(d.get("names", []) or [])
        if "tensor" in d:
            t = d["tensor"]
            values = np.asarray(t.get("values", []), dtype=np.float64)
            shape = tuple(int(s) for s in t.get("shape", []) or [values.size])
            try:
                arr = values.reshape(shape)
            except ValueError as e:
                raise SeldonError(f"tensor values do not fit shape {shape}: {e}")
            return cls(names=names, array=arr, encoding=ENC_TENSOR)
        if "ndarray" in d:
            raw = d["ndarray"]
            arr: Optional[np.ndarray]
            try:
                arr = np.asarray(raw)
                if arr.dtype == object:
                    arr = None
            except Exception:
                arr = None
            return cls(names=names, array=arr, encoding=ENC_NDARRAY, raw_ndarray=raw)
        if "tftensor" in d:
            raise SeldonError(
                "tftensor payloads require tensorflow, which is not available in this "
                "build; use 'tensor' or 'ndarray'",
                status_code=400,
            )
        raise SeldonError("DefaultData requires one of: tensor, ndarray, tftensor")


@dataclass(slots=True)
class SeldonMessage:
    """The one message type flowing through graphs (`proto/prediction.proto:14-24`).

    Exactly one of (data, bin_data, str_data, json_data) is set; ``which`` names
    the active oneof arm ('data' | 'binData' | 'strData' | 'jsonData' | '').
    """

    status: Optional[Status] = None
    meta: Meta = field(default_factory=Meta)
    data: Optional[DefaultData] = None
    bin_data: Optional[bytes] = None
    str_data: Optional[str] = None
    json_data: Any = None
    which: str = ""

    # ---- constructors -------------------------------------------------
    @classmethod
    def from_array(
        cls,
        array: ArrayLike,
        names: Optional[Sequence[str]] = None,
        encoding: str = ENC_TENSOR,
        meta: Optional[Meta] = None,
    ) -> "SeldonMessage":
        return cls(
            meta=meta or Meta(),
            data=DefaultData(names=list(names or []), array=array, encoding=encoding),
            which="data",
        )

    @classmethod
    def from_bytes(cls, payload: bytes, meta: Optional[Meta] = None) -> "SeldonMessage":
        return cls(meta=meta or Meta(), bin_data=payload, which="binData")

    @classmethod
    def from_str(cls, payload: str, meta: Optional[Meta] = None) -> "SeldonMessage":
        return cls(meta=meta or Meta(), str_data=payload, which="strData")

    @classmethod
    def from_json_data(cls, payload: Any, meta: Optional[Meta] = None) -> "SeldonMessage":
        return cls(meta=meta or Meta(), json_data=payload, which="jsonData")

    # ---- payload access ----------------------------------------------
    def payload(self) -> Union[np.ndarray, bytes, str, Any, None]:
        """The user-facing payload: array for data, else bytes/str/json."""
        if self.which == "data" and self.data is not None:
            return self.data.to_numpy()
        if self.which == "binData":
            return self.bin_data
        if self.which == "strData":
            return self.str_data
        if self.which == "jsonData":
            return self.json_data
        return None

    @property
    def names(self) -> List[str]:
        return self.data.names if self.data is not None else []

    # ---- wire codec ---------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {}
        if self.status is not None:
            d["status"] = self.status.to_dict()
        meta_d = self.meta.to_dict()
        # Keep "meta" present (possibly {}) to mirror reference responses which
        # always attach a meta object (`utils.py:construct_response_json`).
        d["meta"] = meta_d
        if self.which == "data" and self.data is not None:
            d["data"] = self.data.to_dict()
        elif self.which == "binData":
            d["binData"] = base64.b64encode(self.bin_data or b"").decode("utf-8")
        elif self.which == "strData":
            d["strData"] = self.str_data
        elif self.which == "jsonData":
            d["jsonData"] = self.json_data
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SeldonMessage":
        if not isinstance(d, dict):
            raise SeldonError(f"SeldonMessage must be a JSON object, got {type(d).__name__}")
        msg = cls(
            status=Status.from_dict(d["status"]) if "status" in d else None,
            meta=Meta.from_dict(d.get("meta")),
        )
        if "data" in d:
            msg.data = DefaultData.from_dict(d["data"])
            msg.which = "data"
        elif "binData" in d:
            raw = d["binData"]
            if isinstance(raw, str):
                try:
                    msg.bin_data = base64.b64decode(raw)
                except Exception as e:
                    raise SeldonError(f"binData is not valid base64: {e}")
            else:
                msg.bin_data = bytes(raw)
            msg.which = "binData"
        elif "strData" in d:
            msg.str_data = d["strData"]
            msg.which = "strData"
        elif "jsonData" in d:
            msg.json_data = d["jsonData"]
            msg.which = "jsonData"
        return msg


@dataclass(slots=True)
class SeldonMessageList:
    """`proto/prediction.proto:60-62`."""

    messages: List[SeldonMessage] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {"seldonMessages": [m.to_dict() for m in self.messages]}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SeldonMessageList":
        return cls(messages=[SeldonMessage.from_dict(m) for m in d.get("seldonMessages", [])])


@dataclass(slots=True)
class Feedback:
    """Reward/truth feedback (`proto/prediction.proto:77-82`)."""

    request: Optional[SeldonMessage] = None
    response: Optional[SeldonMessage] = None
    reward: float = 0.0
    truth: Optional[SeldonMessage] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"reward": self.reward}
        if self.request is not None:
            d["request"] = self.request.to_dict()
        if self.response is not None:
            d["response"] = self.response.to_dict()
        if self.truth is not None:
            d["truth"] = self.truth.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Feedback":
        return cls(
            request=SeldonMessage.from_dict(d["request"]) if "request" in d else None,
            response=SeldonMessage.from_dict(d["response"]) if "response" in d else None,
            reward=float(d.get("reward", 0.0)),
            truth=SeldonMessage.from_dict(d["truth"]) if "truth" in d else None,
        )
