"""Wire contracts: payload schema, graph spec, typed unit parameters.

Mirrors the capability of the reference's `proto/prediction.proto` and
`proto/seldon_deployment.proto` without porting code: payloads are lightweight
Python dataclasses with a JSON codec that is wire-compatible with the
reference's proto-JSON, and the graph spec parses SeldonDeployment-shaped
dicts (CRD-compatible).
"""

from seldon_core_tpu.contracts.payload import (
    DefaultData,
    Feedback,
    Meta,
    Metric,
    SeldonMessage,
    SeldonMessageList,
    Status,
)
from seldon_core_tpu.contracts.graph import (
    PredictiveUnit,
    PredictorSpec,
    SeldonDeploymentSpec,
    UnitImplementation,
    UnitMethod,
    UnitType,
)
from seldon_core_tpu.contracts.parameters import Parameter, parse_parameters

__all__ = [
    "DefaultData",
    "Feedback",
    "Meta",
    "Metric",
    "Parameter",
    "PredictiveUnit",
    "PredictorSpec",
    "SeldonDeploymentSpec",
    "SeldonMessage",
    "SeldonMessageList",
    "Status",
    "UnitImplementation",
    "UnitMethod",
    "UnitType",
    "parse_parameters",
]
