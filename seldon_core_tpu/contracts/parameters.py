"""Typed unit parameters.

The reference delivers per-unit parameters as JSON
``[{"name": ..., "type": "INT|FLOAT|DOUBLE|STRING|BOOL", "value": ...}]`` in
the ``PREDICTIVE_UNIT_PARAMETERS`` env var and coerces values by declared type
(`python/seldon_core/microservice.py:50-87`,
`engine/.../PredictiveUnitState.java:114-120`). Same contract here.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from seldon_core_tpu.contracts.payload import SeldonError

_COERCERS = {
    "INT": int,
    "FLOAT": float,
    "DOUBLE": float,
    "STRING": str,
    "BOOL": lambda v: v if isinstance(v, bool) else str(v).lower() in ("true", "1", "yes"),
}


@dataclass(slots=True)
class Parameter:
    name: str
    value: Any
    type: str = "STRING"

    def typed_value(self) -> Any:
        coercer = _COERCERS.get(self.type.upper())
        if coercer is None:
            raise SeldonError(f"Unknown parameter type {self.type!r} for {self.name!r}")
        try:
            return coercer(self.value)
        except (TypeError, ValueError) as e:
            raise SeldonError(f"Cannot coerce parameter {self.name!r}={self.value!r} to {self.type}: {e}")

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "value": str(self.value), "type": self.type}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Parameter":
        if "name" not in d:
            raise SeldonError("parameter requires a name")
        return cls(name=d["name"], value=d.get("value"), type=d.get("type", "STRING") or "STRING")


def parse_parameters(raw: Optional[str] = None, env: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
    """Parse the PREDICTIVE_UNIT_PARAMETERS contract into {name: typed value}."""
    if raw is None:
        env = env if env is not None else dict(os.environ)
        raw = env.get("PREDICTIVE_UNIT_PARAMETERS", "[]")
    try:
        items = json.loads(raw)
    except json.JSONDecodeError as e:
        raise SeldonError(f"PREDICTIVE_UNIT_PARAMETERS is not valid JSON: {e}")
    if not isinstance(items, list):
        raise SeldonError("PREDICTIVE_UNIT_PARAMETERS must be a JSON list")
    return {p.name: p.typed_value() for p in (Parameter.from_dict(i) for i in items)}
