"""First-class topology: the one place device/mesh/host facts live.

Every other layer — servers, batcher, disagg workers, autoscaler — used to
re-derive the device world (`jax.devices()`, ad-hoc ``Mesh`` construction,
``devices[0]`` defaults, ``slice_index`` probes) at its own call sites,
which is exactly the single-mesh assumption ROADMAP item 1 names as the
scale-out blocker: facts derived twice can disagree, and a slice handed to
a worker has no way to say "this is your world now".

``Topology`` is the declared object those layers consume instead:

* the **axis-name registry** (:data:`DECLARED_AXES`) — the only legal mesh
  axis names; ``tools/shardlint`` statically checks every
  ``PartitionSpec``/collective ``axis_name`` literal against it, and
  :meth:`Topology.mesh` re-checks at runtime, so a typo'd axis fails in
  lint and in the first mesh build rather than as a silent replication.
* the **device world** plus host/process layout (process index/count,
  local devices, physical slice map) — derived ONCE in
  :meth:`Topology.detect` and injected everywhere else.
* **slice views**: :meth:`Topology.sub_topology` hands a disaggregated
  slice a Topology of its own devices, so a prefill or decode slice can
  itself be tensor-parallel sharded (``slice_topo.serving_mesh(tp)``) —
  the pre-work for TP × disaggregation.

Host/slice assumptions (``devices[0]`` defaults, ``process_index == 0``
gating, ``slice_index`` probes) are only legal inside the functions
declared in :data:`SINGLE_HOST_GUARDS`; shardlint's ``host-assumption``
rule enforces that, which is why the registries below are plain literals —
the linter reads them with ``ast`` without importing anything.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field, replace
from typing import Dict, Mapping, Optional, Sequence, Tuple

from seldon_core_tpu.parallel import mesh as _mesh

# ----------------------------------------------------------------------
# declared registries (read statically by tools/shardlint — keep literal)
# ----------------------------------------------------------------------

#: The only legal mesh axis names. Every ``PartitionSpec`` / ``shard_map``
#: / collective ``axis_name`` literal anywhere in the tree must come from
#: this table (shardlint rule ``axis-name-discipline``); Topology.mesh()
#: raises on anything else at runtime.
DECLARED_AXES: Dict[str, str] = {
    "data": "data-parallel replicas; DCN-tolerant (one sync per step)",
    "model": "tensor parallelism (GSPMD); ICI-only, innermost",
    "seq": "sequence parallelism for long context; ICI-only",
    "expert": "expert parallelism for MoE layers",
    "pipe": "pipeline stages; DCN-tolerant point-to-point handoff",
}

#: Functions allowed to touch raw host/process/slice facts
#: (``devices[0]``, ``process_index`` comparisons, ``slice_index``
#: probes). Everything else must consume the Topology predicates
#: (``single_host`` / ``is_primary_process`` / ``default_device``) or
#: carry a reasoned ``# shardlint: allow-host-assumption(...)``.
SINGLE_HOST_GUARDS: Dict[str, str] = {
    "Topology.detect": "the one derivation site for the device world",
    "Topology.default_device": "placement default = first LOCAL device; "
                               "the declared form of devices[0]",
    "Topology.is_primary_process": "process_index == 0 IS this predicate; "
                                   "callers gate on it, not on the index",
    "physical_slice_map": "slice_index probing is the topology layer's "
                          "job; consumers branch on the returned map",
}

#: Constructors/functions that guarantee prefill/decode slice
#: disjointness at runtime, so call sites passing statically-opaque
#: device sets are contract-covered (shardlint rule
#: ``slice-disjointness`` still reports PROVABLE overlaps at any site —
#: a certain overlap is a bug even when the contract turns it into a
#: clean crash).
SLICE_CONTRACTS: Dict[str, str] = {
    "DisaggregatedMesh": "constructor raises ValueError on any "
                         "prefill/decode device overlap",
    "disaggregated_mesh": "delegates to DisaggregatedMesh after "
                          "complement/tail splits of one device list",
    "partition_for_disaggregation": "returns complementary partitions "
                                    "(whole physical slices or "
                                    "tail/head) of a single list",
    "Topology.disaggregated": "delegates counts to disaggregated_mesh, "
                              "which splits one device list into "
                              "complementary halves",
}


def physical_slice_map(devices: Sequence) -> Optional[Dict[int, list]]:
    """``{slice_index: [devices]}`` when every device exposes a physical
    slice id (real multi-slice platforms), else None (CPU test meshes,
    single-slice platforms). The ONE place the ``slice_index`` attribute
    is probed; consumers branch on the returned map, which makes their
    single-slice fallback a declared fact instead of an implicit one."""
    if not devices or not all(hasattr(d, "slice_index") for d in devices):
        return None
    by_slice: Dict[int, list] = {}
    for d in devices:
        by_slice.setdefault(d.slice_index, []).append(d)
    return by_slice


@dataclass(frozen=True)
class Topology:
    """Immutable snapshot of the device world one process serves from.

    ``devices`` is the (sub)world in enumeration order — for the process
    topology that is ``jax.devices()``; for a slice view it is the
    slice's devices. Meshes, disaggregated splits, and placement
    defaults are all derived from here so every consumer agrees."""

    devices: Tuple
    local_devices: Tuple
    process_index: int = 0
    process_count: int = 1
    slice_map: Optional[Mapping[int, tuple]] = field(default=None)

    # -- derivation ----------------------------------------------------

    @classmethod
    def detect(cls) -> "Topology":
        """Derive the process topology from the JAX runtime. The only
        place outside tests that asks JAX for the device world; call
        ``multihost.initialize()`` first on multi-host pods."""
        import jax

        devices = tuple(jax.devices())
        sm = physical_slice_map(devices)
        return cls(
            devices=devices,
            local_devices=tuple(jax.local_devices()),
            process_index=jax.process_index(),
            process_count=jax.process_count(),
            slice_map=None if sm is None else {
                k: tuple(v) for k, v in sm.items()},
        )

    def sub_topology(self, devices: Sequence) -> "Topology":
        """A view of this topology restricted to ``devices`` (a disagg
        slice, a replica's shard, ...). Host/process layout carries
        over; the slice map is re-derived for the subset, so a slice can
        build its own meshes — including TP within the slice."""
        devices = tuple(devices)
        unknown = set(map(id, devices)) - set(map(id, self.devices))
        if unknown:
            raise ValueError(
                f"sub_topology devices not in this topology's world "
                f"({len(unknown)} of {len(devices)} unknown)")
        local = set(map(id, self.local_devices))
        sm = physical_slice_map(devices)
        return replace(
            self,
            devices=devices,
            local_devices=tuple(d for d in devices if id(d) in local),
            slice_map=None if sm is None else {
                k: tuple(v) for k, v in sm.items()},
        )

    # -- host/process predicates (the declared guards) -----------------

    @property
    def device_count(self) -> int:
        return len(self.devices)

    @property
    def local_device_count(self) -> int:
        return len(self.local_devices)

    @property
    def single_host(self) -> bool:
        return self.process_count == 1

    @property
    def is_primary_process(self) -> bool:
        return self.process_index == 0

    @property
    def default_device(self):
        """Placement default: the first device this process can address
        (falls back to the world's first device for pure slice views
        with no local member)."""
        pool = self.local_devices or self.devices
        return pool[0]

    @property
    def num_slices(self) -> int:
        return len(self.slice_map) if self.slice_map else 1

    # -- mesh builders (axis names validated against DECLARED_AXES) ----

    def _check_axes(self, names) -> None:
        unknown = [a for a in names if a not in DECLARED_AXES]
        if unknown:
            raise ValueError(
                f"undeclared mesh axis name(s) {unknown!r}: every axis "
                f"must be registered in parallel/topology.py "
                f"DECLARED_AXES (have: {', '.join(DECLARED_AXES)})")

    def mesh(self, axes: Dict[str, int]):
        """``make_mesh`` over this topology's devices, axis names
        checked against the declared registry."""
        self._check_axes(axes)
        return _mesh.make_mesh(axes, self.devices)

    def serving_mesh(self, model_parallel: int = 1):
        return self.mesh({"data": -1, "model": model_parallel})

    def hybrid_mesh(self, ici_axes: Dict[str, int],
                    dcn_axes: Optional[Dict[str, int]] = None):
        from seldon_core_tpu.parallel.multihost import hybrid_mesh

        self._check_axes(dict(dcn_axes or {}))
        self._check_axes(ici_axes)
        return hybrid_mesh(ici_axes, dcn_axes, self.devices)

    def disaggregated(self, prefill_devices=1, decode_devices=0):
        """Disaggregated prefill/decode split of this topology's world.
        The returned ``DisaggregatedMesh`` carries ``prefill_topology``
        / ``decode_topology`` sub-views so each slice can build further
        meshes (TP inside a slice) without re-deriving anything."""
        dm = _mesh.disaggregated_mesh(
            prefill_devices, decode_devices, devices=self.devices)
        dm.attach_topology(self)
        return dm

    def __repr__(self) -> str:  # keep logs short: devices can be many
        return (f"Topology(devices={self.device_count}, "
                f"process={self.process_index}/{self.process_count}, "
                f"slices={self.num_slices})")


# ----------------------------------------------------------------------
# process singleton (injectable for tests / virtual meshes)
# ----------------------------------------------------------------------

_TOPO_LOCK = threading.Lock()
_PROCESS_TOPOLOGY: Optional[Topology] = None


def get_topology() -> Topology:
    """The process topology, detecting it on first use. Tests and
    virtual-mesh harnesses inject their own via :func:`set_topology`."""
    global _PROCESS_TOPOLOGY
    with _TOPO_LOCK:
        if _PROCESS_TOPOLOGY is None:
            _PROCESS_TOPOLOGY = Topology.detect()
        return _PROCESS_TOPOLOGY


def set_topology(topo: Optional[Topology]) -> Optional[Topology]:
    """Install (or with None, reset) the process topology; returns the
    previous value so callers can restore it."""
    global _PROCESS_TOPOLOGY
    with _TOPO_LOCK:
        prev = _PROCESS_TOPOLOGY
        _PROCESS_TOPOLOGY = topo
        return prev
