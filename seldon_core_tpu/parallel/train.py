"""Sharded training step over a device mesh.

The reference is a serving platform with no training code (SURVEY.md §2
parallelism note), but the TPU build treats distributed execution as
first-class: the same GSPMD machinery that shards a served model also powers
fine-tuning / continued training of the native model families. This module
builds a full optax training step — loss, grad, optimizer update — jitted over
a ``jax.sharding.Mesh`` with Megatron-style tensor parallelism ('model'),
data parallelism ('data'), sequence parallelism ('seq') and expert
parallelism ('expert'). XLA/GSPMD inserts the collectives
(psum/all_gather/reduce_scatter) over ICI.

Used by ``__graft_entry__.dryrun_multichip`` (the driver's multi-chip
compile/execute check) and by tests/test_train.py on a virtual 8-device CPU
mesh.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Callable, Dict, Optional, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import optax
from flax.linen import partitioning as nn_partitioning

from seldon_core_tpu.parallel.sharding import _rules_for_mesh

logger = logging.getLogger(__name__)

# Training rule table: unlike serving (DEFAULT_LOGICAL_RULES, where 'seq' is
# replicated because requests are short), training shards activations along
# the sequence axis too (sequence parallelism for long context).
TRAIN_RULES: Tuple[Tuple[str, Optional[str]], ...] = (
    ("batch", "data"),
    ("seq", "seq"),
    ("embed", None),
    ("heads", "model"),
    ("kv_heads", "model"),
    ("mlp", "model"),
    ("vocab", "model"),
    ("expert", "expert"),
)


class TrainState(flax.struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any


def init_train_state(
    module,
    tx: optax.GradientTransformation,
    mesh,
    example_tokens: jnp.ndarray,
    rules=TRAIN_RULES,
    seed: int = 0,
) -> TrainState:
    """Initialise params sharded per the module's flax logical axis names and
    an optimizer state that inherits the param shardings (sharding
    propagation through a jitted ``tx.init``).

    Params never materialise unsharded: logical specs come from
    ``jax.eval_shape`` over init, and the real init is jitted with
    ``out_shardings`` so each device only ever allocates its shard — required
    for models whose full parameter tree exceeds one device's HBM."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    rules = tuple(_rules_for_mesh(mesh, rules))
    key = jax.random.PRNGKey(seed)

    def init_params(key):
        return module.init(key, example_tokens)["params"]

    replicated = NamedSharding(mesh, P())
    with mesh, nn_partitioning.axis_rules(rules):
        abstract = jax.eval_shape(lambda k: module.init(k, example_tokens), key)
        out_shardings: Any = replicated
        if "params_axes" in abstract:
            import flax.core

            # get_axis_names returns a FrozenDict; params is a plain dict
            logical = flax.core.unfreeze(nn_partitioning.get_axis_names(abstract["params_axes"]))
            is_spec = lambda x: isinstance(x, (tuple, P))  # noqa: E731
            spec_tree = jax.tree.map(
                lambda s: NamedSharding(mesh, P(*nn_partitioning.logical_to_mesh_axes(s, rules=list(rules)))),
                logical,
                is_leaf=is_spec,
            )
            params_struct = jax.tree.structure(abstract["params"])
            if jax.tree.structure(spec_tree) == params_struct:
                out_shardings = spec_tree
            else:
                logger.warning("params/axes tree mismatch; initialising replicated")
        params = jax.jit(init_params, out_shardings=out_shardings)(key)
        opt_state = jax.jit(tx.init)(params)
    return TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=opt_state)


def next_token_loss(module) -> Callable:
    """Causal LM loss: cross-entropy of logits[t] against tokens[t+1]."""

    def loss_fn(params, tokens):
        logits, _ = module.apply({"params": params}, tokens)
        targets = tokens[:, 1:]
        logits = logits[:, :-1].astype(jnp.float32)
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        return loss.mean()

    return loss_fn


def make_train_step(
    module,
    tx: optax.GradientTransformation,
    mesh,
    loss_fn: Optional[Callable] = None,
    rules=TRAIN_RULES,
) -> Callable[[TrainState, jnp.ndarray], Tuple[TrainState, Dict[str, jnp.ndarray]]]:
    """Return ``run(state, tokens) -> (new_state, metrics)``, jitted over the
    mesh with donated state buffers. The axis-rules context is installed
    around the call so flax ``with_sharding_constraint`` logical names inside
    the model resolve to mesh axes at trace time."""
    rules = tuple(_rules_for_mesh(mesh, rules))
    loss_fn = loss_fn or next_token_loss(module)

    def step_fn(state: TrainState, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, tokens)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        new_state = state.replace(step=state.step + 1, params=new_params, opt_state=new_opt)
        return new_state, {"loss": loss, "grad_norm": gnorm}

    jitted = jax.jit(step_fn, donate_argnums=(0,))

    def run(state: TrainState, tokens):
        with mesh, nn_partitioning.axis_rules(rules):
            return jitted(state, tokens)

    return run


def shard_batch(tokens, mesh, batch_axis: str = "data", seq_axis: str = "seq"):
    """device_put a [batch, seq] token array sharded over (data, seq)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    axes = set(mesh.axis_names)
    spec = P(
        batch_axis if batch_axis in axes else None,
        seq_axis if seq_axis in axes else None,
    )
    return jax.device_put(tokens, NamedSharding(mesh, spec))


def save_train_state(state: TrainState, path: str, overwrite: bool = True) -> str:
    """Orbax checkpoint of the full training state (step + params +
    optimizer). Works on sharded state: each host writes its shards.
    ``overwrite`` (default) allows periodic saves to a stable path —
    orbax itself refuses to clobber."""
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(path), state, force=overwrite)
    ckptr.wait_until_finished()
    return path


def restore_train_state(
    path: str,
    module,
    tx: optax.GradientTransformation,
    mesh,
    example_tokens: jnp.ndarray,
    rules=TRAIN_RULES,
) -> TrainState:
    """Restore a TrainState directly into the mesh's shardings. The target
    shardings come from one throwaway sharded init (freed before the restore
    reads anything), so no step ever materialises an unsharded tree — each
    device's peak is one shard-sized allocation."""
    import orbax.checkpoint as ocp

    from jax.sharding import NamedSharding, PartitionSpec as P

    # Shardings for the restore target come from one throwaway sharded init
    # (its per-device allocations are shard-sized and freed before the
    # restore opens anything, so peak memory matches the final state; the
    # cost is one redundant init+tx compile — a zero-allocation derivation
    # via AOT-compiled output shardings can replace this if restore time on
    # the largest models warrants it). Leaves init placed outside the mesh
    # (the step scalar) restore as mesh-replicated, or the restored state
    # would mix device sets.
    live = init_train_state(module, tx, mesh, example_tokens, rules=rules)
    replicated = NamedSharding(mesh, P())

    def shard_of(leaf):
        sh = getattr(leaf, "sharding", None)
        return sh if isinstance(sh, NamedSharding) and sh.mesh == mesh else replicated

    abstract = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=shard_of(l)), live
    )
    del live
    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(os.path.abspath(path), abstract)
