"""Pipeline parallelism over the 'pipe' mesh axis.

The scaling playbook's SPMD pipeline: layers are grouped into S stages, one
per device along 'pipe'; the batch splits into M microbatches; every step
each stage runs its layers on its in-flight microbatch and hands the
activation to the next stage with a single ``ppermute`` hop (ICI
point-to-point within a slice, DCN between slices — 'pipe' is one of the
two DCN-tolerant axes in parallel/multihost.py). The schedule is GPipe:
M + S - 1 steps, bubble fraction (S-1)/(M+S-1), so throughput approaches
ideal as microbatches grow.

Everything is expressed functionally (``shard_map`` + ``lax.scan`` +
masked writes), so the BACKWARD pass needs no hand scheduling: jax.grad
differentiates the forward schedule, and the transposed ppermute carries
gradients stage-to-stage in reverse — the pipeline train step is just
grad-of-pipeline-forward.

Composes with data parallelism: the batch dim shards over 'data' while
stages shard over 'pipe' (each data-parallel group runs its own pipeline).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from seldon_core_tpu.parallel.compat import shard_map


def stack_stage_params(per_stage_params: list) -> Any:
    """[S] list of per-stage pytrees -> one pytree with leading stage dim
    (shard it over 'pipe' before feeding pipeline_apply)."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *per_stage_params)


def pipeline_apply(
    stage_fn: Callable,
    stage_params: Any,
    x: jnp.ndarray,
    mesh,
    n_microbatches: int,
    axis: str = "pipe",
    data_axis: str = "data",
):
    """Run S pipeline stages over the batch.

    stage_fn(params, x) -> y with y.shape == x.shape (transformer-block
    convention: stages preserve the activation shape).
    stage_params: pytree with leading dim S (stage-stacked).
    x: [B, ...]; B must divide into n_microbatches per data shard.
    Returns [B, ...] outputs, numerically identical to applying the stages
    sequentially.
    """
    S = dict(mesh.shape)[axis]
    M = int(n_microbatches)
    dp = dict(mesh.shape).get(data_axis, 1)
    if x.shape[0] % (M * dp):
        raise ValueError(
            f"batch {x.shape[0]} must divide into {M} microbatches per "
            f"{dp} data shard(s)"
        )
    n_stages = jax.tree.leaves(stage_params)[0].shape[0]
    if n_stages != S:
        # a mismatch that still divides would silently run a subset of stages
        raise ValueError(
            f"stage_params has {n_stages} stages but the '{axis}' axis has "
            f"{S} devices (one stage per device)"
        )

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis), P(data_axis)),
        out_specs=P(data_axis),
        check_rep=False,
    )
    def run(params, x_local):
        params = jax.tree.map(lambda a: a[0], params)  # this device's stage
        stage_id = jax.lax.axis_index(axis)
        mb = x_local.shape[0] // M
        xs = x_local.reshape(M, mb, *x_local.shape[1:])

        def step(carry, t):
            recv, outputs = carry
            # stage 0 ingests microbatch t (clamped; masked out later),
            # other stages consume what the previous stage sent
            inp_idx = jnp.clip(t, 0, M - 1)
            feed = jax.lax.dynamic_index_in_dim(xs, inp_idx, keepdims=False)
            cur = jnp.where(stage_id == 0, feed, recv)
            y = stage_fn(params, cur)
            # last stage completes microbatch t-(S-1)
            out_idx = t - (S - 1)
            valid = (stage_id == S - 1) & (out_idx >= 0) & (out_idx < M)
            updated = jax.lax.dynamic_update_index_in_dim(
                outputs, y.astype(outputs.dtype), jnp.clip(out_idx, 0, M - 1), 0
            )
            outputs = jnp.where(valid, updated, outputs)
            # hand the activation to the next stage (no wraparound: stage 0
            # reads fresh microbatches, so its incoming slot is unused)
            recv = jax.lax.ppermute(y, axis, [(i, i + 1) for i in range(S - 1)])
            return (recv, outputs), None

        recv0 = jnp.zeros_like(xs[0])
        out0 = jnp.zeros_like(xs)
        (_, outputs), _ = jax.lax.scan(
            step, (recv0, out0), jnp.arange(M + S - 1)
        )
        # only the last stage holds real outputs; psum replicates them over
        # 'pipe' so the result is well-defined on every device
        outputs = jnp.where(stage_id == S - 1, outputs, jnp.zeros_like(outputs))
        outputs = jax.lax.psum(outputs, axis)
        return outputs.reshape(x_local.shape)

    return run(stage_params, x)


def make_pipeline_train_step(
    stage_fn: Callable,
    loss_fn: Callable,
    tx,
    mesh,
    n_microbatches: int,
    axis: str = "pipe",
):
    """Pipeline-parallel training: grad of the pipelined forward. loss_fn
    maps (outputs, batch) -> scalar. Returns run(params, opt_state, batch)
    -> (params, opt_state, loss); params carry the stage-stacked layout
    sharded over 'pipe'."""
    import optax

    def objective(params, batch):
        out = pipeline_apply(stage_fn, params, batch["x"], mesh, n_microbatches, axis=axis)
        return loss_fn(out, batch)

    @jax.jit
    def run(params, opt_state, batch):
        loss, grads = jax.value_and_grad(objective)(params, batch)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return run
