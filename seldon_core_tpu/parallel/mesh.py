"""Device meshes: the scaling substrate.

The reference scales by Kubernetes replicas + HPA and pays the pod network for
every hop (SURVEY.md §2 parallelism note). Here scaling is a
``jax.sharding.Mesh`` over TPU chips: data-parallel replica serving ('data'),
GSPMD tensor parallelism ('model'), sequence parallelism for long context
('seq'), expert parallelism ('expert') and pipeline stages ('pipe'). XLA lowers
the resulting collectives onto ICI within a slice and DCN across slices.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence

import numpy as np


def make_mesh(
    axes: Dict[str, int],
    devices: Optional[Sequence] = None,
):
    """Build a Mesh with the given {axis_name: size}. Sizes of -1 are inferred
    from the device count (at most one -1). Axis order is preserved; ICI-heavy
    axes ('model', 'seq') should come last so neighboring devices serve them."""
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    sizes = dict(axes)
    unknown = [k for k, v in sizes.items() if v == -1]
    if len(unknown) > 1:
        raise ValueError(f"At most one mesh axis may be -1, got {unknown}")
    known = math.prod(v for v in sizes.values() if v != -1)
    if unknown:
        if n % known:
            raise ValueError(f"{n} devices not divisible by fixed axes product {known}")
        sizes[unknown[0]] = n // known
    total = math.prod(sizes.values())
    if total != n:
        raise ValueError(f"Mesh axes {sizes} need {total} devices, have {n}")
    mesh_devices = np.array(devices).reshape(*sizes.values())
    return Mesh(mesh_devices, tuple(sizes.keys()))


def serving_mesh(model_parallel: int = 1, devices: Optional[Sequence] = None):
    """Standard serving mesh: ('data', 'model') with tp innermost for ICI."""
    return make_mesh({"data": -1, "model": model_parallel}, devices)
