"""Device meshes: the scaling substrate.

The reference scales by Kubernetes replicas + HPA and pays the pod network for
every hop (SURVEY.md §2 parallelism note). Here scaling is a
``jax.sharding.Mesh`` over TPU chips: data-parallel replica serving ('data'),
GSPMD tensor parallelism ('model'), sequence parallelism for long context
('seq'), expert parallelism ('expert') and pipeline stages ('pipe'). XLA lowers
the resulting collectives onto ICI within a slice and DCN across slices.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence

import numpy as np


def make_mesh(
    axes: Dict[str, int],
    devices: Optional[Sequence] = None,
):
    """Build a Mesh with the given {axis_name: size}. Sizes of -1 are inferred
    from the device count (at most one -1). Axis order is preserved; ICI-heavy
    axes ('model', 'seq') should come last so neighboring devices serve them."""
    from jax.sharding import Mesh

    if devices is None:
        from seldon_core_tpu.parallel.topology import get_topology

        devices = list(get_topology().devices)
    else:
        devices = list(devices)
    n = len(devices)
    sizes = dict(axes)
    unknown = [k for k, v in sizes.items() if v == -1]
    if len(unknown) > 1:
        raise ValueError(f"At most one mesh axis may be -1, got {unknown}")
    known = math.prod(v for v in sizes.values() if v != -1)
    if unknown:
        if n % known:
            raise ValueError(f"{n} devices not divisible by fixed axes product {known}")
        sizes[unknown[0]] = n // known
    total = math.prod(sizes.values())
    if total != n:
        raise ValueError(f"Mesh axes {sizes} need {total} devices, have {n}")
    mesh_devices = np.array(devices).reshape(*sizes.values())
    return Mesh(mesh_devices, tuple(sizes.keys()))


def serving_mesh(model_parallel: int = 1, devices: Optional[Sequence] = None):
    """Standard serving mesh: ('data', 'model') with tp innermost for ICI."""
    return make_mesh({"data": -1, "model": model_parallel}, devices)


class DisaggregatedMesh:
    """A serving mesh split into a PREFILL slice and a DECODE slice
    (DistServe/Splitwise): the compute-bound admission burst runs on one
    set of chips, the bandwidth-bound decode batch on a disjoint set, and
    the prefilled KV moves between them device-to-device
    (runtime/disagg.py). Each role carries its own sub-mesh so
    tensor/sequence parallelism can still shard WITHIN a slice."""

    def __init__(self, prefill_devices: Sequence, decode_devices: Sequence):
        self.prefill_devices = list(prefill_devices)
        self.decode_devices = list(decode_devices)
        self.prefill_topology = None  # set by attach_topology
        self.decode_topology = None
        if not self.prefill_devices or not self.decode_devices:
            raise ValueError(
                f"disaggregated mesh needs >=1 device per role, got "
                f"{len(self.prefill_devices)} prefill / "
                f"{len(self.decode_devices)} decode")
        overlap = set(map(id, self.prefill_devices)) & set(
            map(id, self.decode_devices))
        if overlap:
            raise ValueError(
                "prefill and decode slices overlap: a shared device would "
                "re-couple the prefill burst to decode latency — the exact "
                "interference disaggregation exists to remove")
        self.prefill = serving_mesh(devices=self.prefill_devices)
        self.decode = serving_mesh(devices=self.decode_devices)

    def attach_topology(self, topo) -> "DisaggregatedMesh":
        """Give each slice a Topology view of its own devices
        (parallel/topology.py), so a slice can build further meshes —
        e.g. tensor parallelism WITHIN the prefill or decode slice —
        without re-deriving the device world."""
        self.prefill_topology = topo.sub_topology(self.prefill_devices)
        self.decode_topology = topo.sub_topology(self.decode_devices)
        return self

    def __repr__(self) -> str:
        return (f"DisaggregatedMesh(prefill={len(self.prefill_devices)}, "
                f"decode={len(self.decode_devices)})")


def disaggregated_mesh(
    prefill_devices=1,
    decode_devices=0,
    devices: Optional[Sequence] = None,
) -> DisaggregatedMesh:
    """Split the device world into a prefill slice and a decode slice.

    ``prefill_devices`` / ``decode_devices`` are either explicit device
    sequences or counts. With counts, the prefill slice takes devices from
    the END of the enumeration and decode from the front (0 = all the
    rest): on multi-slice platforms device enumeration is slice-major, so
    the roles land on distinct physical slices and the handoff crosses
    ICI/DCN exactly once (parallel/multihost.py
    ``partition_for_disaggregation`` refines the split along physical
    slice boundaries when the platform exposes them)."""
    if not isinstance(prefill_devices, int) and not isinstance(
            decode_devices, int):
        return DisaggregatedMesh(prefill_devices, decode_devices)

    from seldon_core_tpu.parallel.multihost import (
        partition_for_disaggregation)

    if devices is None:
        # the injected process topology, not a fresh jax.devices() — the
        # split must agree with every other consumer's world view
        from seldon_core_tpu.parallel.topology import get_topology

        devices = list(get_topology().devices)
    else:
        devices = list(devices)
    if not isinstance(prefill_devices, int):
        pre = list(prefill_devices)
        taken = set(map(id, pre))
        rest = [d for d in devices if id(d) not in taken]
        n_dec = int(decode_devices) or len(rest)
        return DisaggregatedMesh(pre, rest[:n_dec])
    if not isinstance(decode_devices, int):
        dec = list(decode_devices)
        taken = set(map(id, dec))
        rest = [d for d in devices if id(d) not in taken]
        n_pre = int(prefill_devices) or len(rest)
        return DisaggregatedMesh(rest[-n_pre:], dec)
    n_pre = int(prefill_devices) or 1
    if n_pre >= len(devices):
        raise ValueError(
            f"prefill_devices={n_pre} leaves no decode devices out of "
            f"{len(devices)}")
    pre, dec = partition_for_disaggregation(devices, n_pre)
    if decode_devices:
        dec = dec[: int(decode_devices)]
    return DisaggregatedMesh(pre, dec)
