"""JAX version compatibility for ``shard_map``.

The API moved twice under us: it grew up in
``jax.experimental.shard_map.shard_map`` (keyword ``check_rep``), was
promoted to ``jax.shard_map`` in newer releases, and the promotion renamed
the replication-check keyword to ``check_vma``. Every caller in this tree
imports from HERE so the resolution happens exactly once:

    from seldon_core_tpu.parallel.compat import shard_map

The shim keeps the OLD keyword name (``check_rep``) as its public surface
— the tree predates the rename — and translates when running on a JAX
that wants ``check_vma``.

Routing through this module is ENFORCED: graftlint's ``compat-drift``
rule flags any direct ``jax.shard_map`` / ``jax.experimental.shard_map``
/ ``jax.lax.axis_size`` use outside this file (docs/static-analysis.md).
"""

from __future__ import annotations

try:  # newer JAX: promoted API, check_vma keyword
    from jax import shard_map as _shard_map_impl

    _CHECK_KW = "check_vma"
except ImportError:  # older JAX: experimental API, check_rep keyword
    from jax.experimental.shard_map import shard_map as _shard_map_impl

    _CHECK_KW = "check_rep"

__all__ = ["shard_map", "axis_size"]


def shard_map(f, *, mesh, in_specs, out_specs, check_rep: bool = False):
    """``jax.shard_map`` resolved across JAX versions (see module docs)."""
    return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, **{_CHECK_KW: check_rep})


def axis_size(axis_name) -> int:
    """Size of a mapped mesh axis from inside a shard_map/pmap body.

    ``jax.lax.axis_size`` only exists on newer JAX; older versions get the
    same static int from ``psum(1, axis)`` (a constant fold — the reduction
    of 1 over the axis is the axis size, resolved at trace time).
    """
    import jax

    impl = getattr(jax.lax, "axis_size", None)
    if impl is not None:
        return impl(axis_name)
    return jax.lax.psum(1, axis_name)
