from seldon_core_tpu.parallel.mesh import make_mesh
from seldon_core_tpu.parallel.sharding import (
    DEFAULT_LOGICAL_RULES,
    shard_apply,
    shard_params,
)
from seldon_core_tpu.parallel.topology import (
    DECLARED_AXES,
    Topology,
    get_topology,
    set_topology,
)

__all__ = [
    "DECLARED_AXES",
    "DEFAULT_LOGICAL_RULES",
    "Topology",
    "get_topology",
    "make_mesh",
    "set_topology",
    "shard_apply",
    "shard_params",
]
