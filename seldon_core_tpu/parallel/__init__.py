from seldon_core_tpu.parallel.mesh import make_mesh
from seldon_core_tpu.parallel.sharding import (
    DEFAULT_LOGICAL_RULES,
    shard_apply,
    shard_params,
)

__all__ = ["DEFAULT_LOGICAL_RULES", "make_mesh", "shard_apply", "shard_params"]
