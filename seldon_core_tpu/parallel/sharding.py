"""Parameter + activation sharding via GSPMD.

Models in seldon_core_tpu.models carry flax *logical* axis names on their
params (param_with_axes). This module maps logical names onto mesh axes with a
rule table and jits the apply function with NamedShardings, letting XLA insert
all_gather/reduce_scatter/psum over ICI — the TPU-native replacement for the
reference's replica-per-pod scaling (SURVEY.md §2 parallelism note).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

# logical axis -> mesh axis (None = replicated). Megatron-style layout:
# hidden/ffn/head dims shard over 'model'; batch over 'data'; sequence over
# 'seq' (long-context); experts over 'expert'.
DEFAULT_LOGICAL_RULES: Tuple[Tuple[str, Optional[str]], ...] = (
    ("batch", "data"),
    ("seq", None),
    ("embed", None),
    ("heads", "model"),
    ("kv_heads", "model"),
    ("mlp", "model"),
    ("vocab", "model"),
    ("expert", "expert"),
)


def _rules_for_mesh(mesh, rules) -> list:
    """Drop rules whose mesh axis doesn't exist on this mesh."""
    available = set(mesh.axis_names)
    out = []
    for logical, physical in rules:
        out.append((logical, physical if physical in available else None))
    return out


def logical_axis_tree(module, example_input):
    """Abstract-init the module to recover the logical PartitionSpec tree for
    its params (the 'params_axes' collection), without allocating memory."""
    import jax
    from flax.linen import partitioning as nn_partitioning

    def _init():
        x = example_input
        if isinstance(x, jax.ShapeDtypeStruct):
            x = jax.numpy.zeros(x.shape, x.dtype)
        return module.init(jax.random.PRNGKey(0), x)

    abstract = jax.eval_shape(_init)
    if "params_axes" not in abstract:
        return None
    return nn_partitioning.get_axis_names(abstract["params_axes"])


def shard_params(params: Any, mesh, logical_specs: Any, rules=DEFAULT_LOGICAL_RULES):
    """device_put the param pytree with NamedShardings from logical specs.
    Params without a spec (or when logical_specs is None) are replicated.

    Int8-quantized leaves (ops.quantize.QuantizedTensor) shard too: the
    weight's logical spec applies to ``q`` unchanged (same shape as the
    original float leaf), and the per-output-channel ``scale`` [C] takes the
    spec's LAST axis (the channel dim it broadcasts over) — so int8 serving
    composes with tensor parallelism instead of excluding it."""
    import jax
    from flax.linen import partitioning as nn_partitioning
    from jax.sharding import NamedSharding, PartitionSpec as P

    from seldon_core_tpu.ops.quantize import QuantizedTensor

    rules = _rules_for_mesh(mesh, rules)
    replicated = NamedSharding(mesh, P())

    def is_q(x) -> bool:
        return isinstance(x, QuantizedTensor)

    if logical_specs is None:
        return jax.device_put(params, replicated)

    def to_mesh_spec(spec):
        return nn_partitioning.logical_to_mesh_axes(spec, rules=rules)

    def to_sharding(spec):
        return NamedSharding(mesh, P(*to_mesh_spec(spec)))

    flat_p, treedef_p = jax.tree.flatten(params, is_leaf=is_q)
    specs_for_params = _align_specs(params, logical_specs, extra_leaf=is_q)
    flat_s, _ = jax.tree.flatten(specs_for_params, is_leaf=lambda x: x is None or _is_spec(x))
    if len(flat_s) != len(flat_p):
        logger.warning("param/spec tree mismatch (%d vs %d); replicating params", len(flat_p), len(flat_s))
        return jax.device_put(params, replicated)
    out = []
    for p, s in zip(flat_p, flat_s):
        if is_q(p):
            if s is not None:
                mesh_spec = list(to_mesh_spec(s))
                wsh = NamedSharding(mesh, P(*mesh_spec))
                last = mesh_spec[-1] if mesh_spec else None
                ssh = NamedSharding(mesh, P(last))
            else:
                wsh = ssh = replicated
            out.append(QuantizedTensor(
                q=jax.device_put(p.q, wsh),
                scale=jax.device_put(p.scale, ssh),
                orig_dtype=p.orig_dtype,
            ))
        else:
            out.append(jax.device_put(p, to_sharding(s) if s is not None else replicated))
    return jax.tree.unflatten(treedef_p, out)


def _is_spec(x) -> bool:
    from jax.sharding import PartitionSpec

    return isinstance(x, (tuple, PartitionSpec))


def _align_specs(params: Any, logical_specs: Any, extra_leaf=None):
    """The params tree may contain collections (params/batch_stats) while the
    axes tree covers only 'params'. Walk params and pull matching specs, None
    where absent. ``extra_leaf`` marks additional leaf types (quantized
    tensors) so the walk doesn't descend into them."""
    import jax

    spec_map = {}

    def record(path, leaf):
        spec_map[tuple(str(k) for k in path)] = leaf

    jax.tree_util.tree_map_with_path(record, logical_specs, is_leaf=_is_spec)

    def lookup(path, leaf):
        key = tuple(str(k) for k in path)
        # try suffix match: params tree has a leading collection key
        if key in spec_map:
            return spec_map[key]
        if len(key) > 1 and key[1:] in spec_map:
            return spec_map[key[1:]]
        return None

    return jax.tree_util.tree_map_with_path(lookup, params, is_leaf=extra_leaf)


def sharding_report(params: Any) -> dict:
    """Inspect the actual ``.sharding`` of every array leaf: how many leaves
    are sharded vs replicated, and which mesh axes carry shards. This is the
    guard against the silent full-replication fallback — tests and strict
    callers assert on it rather than trusting that shard_params worked."""
    import jax
    from jax.sharding import NamedSharding

    report = {"sharded": 0, "replicated": 0, "other": 0, "axes": set()}

    def visit(leaf):
        sh = getattr(leaf, "sharding", None)
        if not isinstance(sh, NamedSharding):
            report["other"] += 1
            return
        axes = set()
        for entry in sh.spec:
            if entry is None:
                continue
            axes.update(entry if isinstance(entry, tuple) else (entry,))
        # Axes of size 1 don't partition anything.
        axes = {a for a in axes if sh.mesh.shape[a] > 1}
        if axes:
            report["sharded"] += 1
            report["axes"] |= axes
        else:
            report["replicated"] += 1

    jax.tree.map(visit, params)
    return report


def shard_apply(
    apply_fn: Callable,
    module,
    params: Any,
    mesh,
    rules=None,
    example_input=None,
    batch_axis: str = "data",
    strict: bool = False,
):
    """Return (jitted_apply, sharded_params) for mesh execution.

    - params shard per the module's logical axis names (replicated fallback);
    - inputs/outputs shard their leading batch dim over ``batch_axis``;
    - the mesh is installed as context so flax sharding constraints resolve.
    - ``strict=True`` raises if the mesh has a non-trivial parameter axis
      (any axis other than ``batch_axis`` with size > 1) but no param leaf
      actually sharded over it — i.e. the replication fallback fired on a
      mesh that was supposed to partition the model.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    rules = tuple(rules) if rules is not None else DEFAULT_LOGICAL_RULES

    logical_specs = None
    if example_input is not None:
        try:
            logical_specs = logical_axis_tree(module, example_input)
        except Exception as e:
            logger.warning("could not derive logical axes (%s); replicating params", e)
    sharded_params = shard_params(params, mesh, logical_specs, rules)

    param_axes = {a for a in mesh.axis_names if a != batch_axis and mesh.shape[a] > 1}
    if param_axes:
        report = sharding_report(sharded_params)
        if not (report["axes"] & param_axes):
            msg = (
                f"mesh has parameter axes {sorted(param_axes)} but every param "
                f"leaf is replicated (report: sharded={report['sharded']} "
                f"replicated={report['replicated']}) — the logical-axis spec "
                "did not align with the param tree"
            )
            if strict:
                raise ValueError(msg)
            logger.warning(msg)

    batch_sharding = NamedSharding(mesh, P(batch_axis))
    replicated = NamedSharding(mesh, P())

    jitted = jax.jit(
        apply_fn,
        in_shardings=(None, batch_sharding),
        out_shardings=batch_sharding,
    )

    def run(p, x):
        with mesh:
            return jitted(p, x)

    return run, sharded_params
