"""Multi-host bootstrap + hybrid ICI/DCN meshes.

The reference scales across hosts with Kubernetes replicas over the pod
network (SURVEY.md §2 parallelism note). The TPU-native equivalent is a
multi-host JAX runtime: every host runs the same program,
``jax.distributed`` wires the processes into one device world, and a
*hybrid* mesh lays parallelism axes so that bandwidth-hungry collectives
(tensor/sequence parallel) ride ICI inside a slice while only
gradient/data-parallel traffic crosses DCN between slices — the layout the
scaling playbook prescribes.

Nothing here requires multiple hosts to import or test: ``initialize()`` is
a no-op on a single process, and ``hybrid_mesh`` degrades to a plain
single-granule mesh when there is one slice.
"""

from __future__ import annotations

import logging
import os
from typing import Dict, Optional, Sequence

logger = logging.getLogger(__name__)

# DCN-tolerant axes: one all-reduce per step (data parallel) or point-to-point
# stage handoff (pipeline). Everything else belongs on ICI.
DCN_FRIENDLY_AXES = ("data", "pipe")


def coordinator_config(env: Optional[Dict[str, str]] = None) -> Optional[Dict[str, object]]:
    """Resolve the distributed-init triple from the environment, or None for
    single-host. Accepts the standard JAX env (JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID) and the common launcher spellings
    (COORDINATOR_ADDRESS, NUM_PROCESSES/WORLD_SIZE, PROCESS_ID/RANK)."""
    env = env if env is not None else dict(os.environ)

    def pick(*names: str) -> Optional[str]:
        for n in names:
            v = env.get(n)
            if v:
                return v
        return None

    addr = pick("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS")
    if not addr:
        return None
    n = pick("JAX_NUM_PROCESSES", "NUM_PROCESSES", "WORLD_SIZE")
    pid = pick("JAX_PROCESS_ID", "PROCESS_ID", "RANK")
    if n is None or pid is None:
        raise ValueError(
            "coordinator address set but process count/id missing: need "
            "JAX_NUM_PROCESSES (or WORLD_SIZE) and JAX_PROCESS_ID (or RANK)"
        )
    return {
        "coordinator_address": addr,
        "num_processes": int(n),
        "process_id": int(pid),
    }


def initialize(env: Optional[Dict[str, str]] = None) -> bool:
    """Join the multi-host world if the environment describes one; returns
    whether distributed init ran. Call once, before any backend use — same
    contract as ``jax.distributed.initialize``."""
    cfg = coordinator_config(env)
    if cfg is None:
        logger.debug("single-host: skipping jax.distributed.initialize")
        return False
    import jax

    jax.distributed.initialize(**cfg)  # type: ignore[arg-type]
    logger.info(
        "joined distributed world: process %s/%s via %s",
        cfg["process_id"], cfg["num_processes"], cfg["coordinator_address"],
    )
    return True


def partition_for_disaggregation(devices: Sequence, prefill_count: int):
    """Split ``devices`` into (prefill, decode) slices for disaggregated
    serving (parallel/mesh.py ``disaggregated_mesh``), preferring PHYSICAL
    slice boundaries: the KV handoff then crosses between slices exactly
    once (ICI within a slice, DCN across), instead of cutting a slice in
    half and paying intra-slice collectives on both sides of the split.

    The prefill slice takes whole physical slices from the END of the
    enumeration when the per-slice device count divides ``prefill_count``;
    otherwise (CPU test mesh, single-slice platforms, ragged counts) the
    split is a plain contiguous tail — device enumeration is slice-major
    on real pods, so the tail is still the "farthest" granule."""
    from seldon_core_tpu.parallel.topology import physical_slice_map

    devices = list(devices)
    n = int(prefill_count)
    if not (0 < n < len(devices)):
        raise ValueError(
            f"prefill_count={n} must leave >=1 decode device out of "
            f"{len(devices)}")
    # the declared slice map, not an inline slice_index probe: when it is
    # None the platform exposes no physical slices and the tail split
    # below is the declared single-granule behavior, not an accident
    by_slice = physical_slice_map(devices)
    if by_slice is not None:
        sizes = {len(v) for v in by_slice.values()}
        if len(by_slice) > 1 and len(sizes) == 1:
            per_slice = sizes.pop()
            if n % per_slice == 0 and n // per_slice < len(by_slice):
                order = sorted(by_slice)
                pre_slices = order[-(n // per_slice):]
                pre = [d for s in pre_slices for d in by_slice[s]]
                dec = [d for s in order[: len(order) - len(pre_slices)]
                       for d in by_slice[s]]
                return pre, dec
        logger.debug(
            "prefill_count %d does not align with physical slices; "
            "falling back to a contiguous tail split", n)
    return devices[-n:], devices[:-n]


def hybrid_mesh(
    ici_axes: Dict[str, int],
    dcn_axes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence] = None,
):
    """Mesh over multiple slices: ``dcn_axes`` partition across slices (keep
    to DCN_FRIENDLY_AXES), ``ici_axes`` partition within a slice. With no
    dcn_axes (or one slice) this is a plain mesh of the ici_axes.

    Sizes of -1 are inferred: at most one per group (ici from per-slice
    device count, dcn from slice count)."""
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    from seldon_core_tpu.parallel.mesh import make_mesh
    from seldon_core_tpu.parallel.topology import (
        get_topology,
        physical_slice_map,
    )

    if devices is None:
        devices = list(get_topology().devices)
    else:
        devices = list(devices)
    dcn_axes = dict(dcn_axes or {})
    if -1 in dcn_axes.values():
        raise ValueError("dcn axis sizes must be explicit (slice count is not inferable)")
    if not dcn_axes or all(v == 1 for v in dcn_axes.values()):
        return make_mesh({**dcn_axes, **ici_axes}, devices)

    for axis in dcn_axes:
        if axis not in DCN_FRIENDLY_AXES:
            logger.warning(
                "axis %r crosses DCN; tensor/seq-parallel collectives over DCN "
                "will dominate step time (keep them on ICI)", axis
            )

    import math

    n = len(devices)
    dcn_known = math.prod(dcn_axes.values())
    ici_known = math.prod(v for v in ici_axes.values() if v != -1)
    per_slice = n // dcn_known
    if n % dcn_known:
        raise ValueError(f"{n} devices not divisible by dcn product {dcn_known}")
    ici = dict(ici_axes)
    unknown = [k for k, v in ici.items() if v == -1]
    if len(unknown) > 1:
        raise ValueError(f"at most one -1 ici axis, got {unknown}")
    if unknown:
        if per_slice % ici_known:
            raise ValueError(f"{per_slice} per-slice devices not divisible by {ici_known}")
        ici[unknown[0]] = per_slice // ici_known

    axis_names = list(dcn_axes.keys()) + list(ici.keys())
    mesh_shape = [1] * len(dcn_axes) + list(ici.values())
    dcn_shape = list(dcn_axes.values()) + [1] * len(ici)
    if physical_slice_map(devices) is not None:
        # real multi-slice platform: let mesh_utils group by slice; layout
        # errors here are real errors and must propagate
        mesh_devices = mesh_utils.create_hybrid_device_mesh(
            mesh_shape, dcn_shape, devices=devices, allow_split_physical_axes=True
        )
    else:
        # Declared single-granule fallback (physical_slice_map returned
        # None: CPU mesh in tests, single-slice platforms): group
        # contiguously — device enumeration is slice-major on real pods,
        # so granule = contiguous block.
        import numpy as np

        logger.debug("no physical slice map; contiguous hybrid grouping")
        mesh_devices = np.array(devices).reshape(
            *dcn_axes.values(), *ici.values()
        )
    return Mesh(mesh_devices, tuple(axis_names))
