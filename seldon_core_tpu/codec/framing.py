"""Zero-copy tensor framing: the binary wire format for inter-hop tensors.

The reference shipped an experimental FlatBuffers transport because the
JSON codec was the data-plane tax on every engine->node hop (PAPER.md
language census; `fbs/prediction.fbs`): a float64 rides proto-JSON as
~18 text bytes plus the `ravel().tolist()` boxing on encode and a
float64 re-parse on decode. This module replaces that with a
length-delimited frame — fixed header, JSON metadata section, raw
concatenated tensor buffers — so a hop ships ndarray BYTES:

    offset  size  field
    0       4     magic  b"SFRM"
    4       2     version (u16 LE)
    6       2     flags   (u16 LE, reserved)
    8       4     n_tensors (u32 LE)
    12      4     meta_len  (u32 LE) — UTF-8 JSON byte length
    16      8     payload_len (u64 LE) — total raw tensor bytes
    24      ...   n_tensors table entries:
                    dtype_code u8 | ndim u8 | reserved u16 |
                    offset u64 | nbytes u64 | ndim x dim u64
    ...     ...   meta JSON (UTF-8)
    ...     ...   tensor payload (concatenated raw buffers; offsets in
                  the table are relative to the payload start)

Header + table + metadata come BEFORE the payload on purpose: a frame
truncated mid-payload still yields its metadata (job ids, status) via
``decode_frame(buf, meta_only=True)``, which is how the network KV
handoff surfaces a per-request error instead of losing the request.

Robustness contract (the fuzz suite, tests/test_framing.py): every
malformed input raises :class:`FrameError` (a 400 ``SeldonError``) —
never a hang, never a partial ndarray, and never an allocation sized by
attacker-controlled fields. The decoder only ever SLICES the received
buffer: declared lengths are validated against ``len(buf)`` before any
``np.frombuffer``, so an "oversized declared length" costs a comparison,
not memory.

Transfer discipline: frame assembly makes exactly ONE bulk
device->host transfer (`jax.device_get` on the full leaf list), never a
per-tensor sync — enforced by the graftlint host-sync checker's framing
egress scope (tools/graftlint/checkers/hostsync.py).
"""

from __future__ import annotations

import json
import struct
import threading
import time
from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

from seldon_core_tpu.contracts.payload import (
    DefaultData,
    Meta,
    SeldonError,
    SeldonMessage,
    Status,
)

CONTENT_TYPE_FRAME = "application/x-seldon-frame"

MAGIC = b"SFRM"
VERSION = 1

_HEADER = struct.Struct("<4sHHIIQ")          # magic, version, flags, n, meta, payload
_ENTRY = struct.Struct("<BBHQQ")             # dtype, ndim, reserved, offset, nbytes
_DIM = struct.Struct("<Q")

# sanity bounds: a frame violating these is malformed, not merely large.
# They cap TABLE/METADATA parsing work — tensor payload size is bounded by
# the transport (client_max_size / grpc max message size), and the decoder
# never allocates from declared fields anyway.
MAX_TENSORS = 4096
MAX_NDIM = 16
MAX_META_BYTES = 64 << 20

# wire dtype codes. Order is the wire contract — append only.
_DTYPE_NAMES = (
    "float32", "float64", "float16", "bfloat16",
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64",
    "bool",
)
_CODE_BY_NAME = {n: i for i, n in enumerate(_DTYPE_NAMES)}


def _np_dtype(name: str) -> np.dtype:
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


class FrameError(SeldonError):
    """Malformed frame: always a clean 400 at the transport edge."""

    def __init__(self, message: str, status_code: int = 400):
        super().__init__(message, status_code=status_code,
                         reason="MALFORMED_FRAME")


# ---------------------------------------------------------------------------
# codec stats (the metrics satellite): lifetime byte tallies per path plus
# encode/decode time samples, drained by MetricsRegistry.sync_framing()
# ---------------------------------------------------------------------------

_stats_lock = threading.Lock()
_encode_times_s: List[float] = []
_decode_times_s: List[float] = []
_bytes_total: Dict[str, int] = {}


def _record(kind: str, path: str, nbytes: int, dur_s: float) -> None:
    with _stats_lock:
        if kind == "encode":
            _encode_times_s.append(dur_s)
        else:
            _decode_times_s.append(dur_s)
        _bytes_total[path] = _bytes_total.get(path, 0) + int(nbytes)


def frame_stats(drain: bool = True) -> Dict[str, Any]:
    """Codec tallies for /metrics: time samples (drained — each observed
    once) and lifetime byte totals per path label (monotonic; the
    registry's counter catch-up converts them to increments)."""
    with _stats_lock:
        out = {
            "frame_encode_times_s": list(_encode_times_s),
            "frame_decode_times_s": list(_decode_times_s),
            "frame_bytes_total": dict(_bytes_total),
        }
        if drain:
            _encode_times_s.clear()
            _decode_times_s.clear()
        return out


# ---------------------------------------------------------------------------
# frame codec: (meta dict, [ndarray]) <-> bytes
# ---------------------------------------------------------------------------

def _host_tensors(tensors: Sequence[Any]) -> List[np.ndarray]:
    """Materialize every tensor on the host with ONE bulk transfer.

    Device arrays (anything that is not already np.ndarray) are pulled in
    a single ``jax.device_get`` over the whole list — per-tensor syncs
    would serialize host and device once per leaf (the PR 3 stall class;
    the graftlint framing-egress rule pins this shape).
    """
    if any(not isinstance(t, np.ndarray) for t in tensors):
        import jax

        # graftlint: allow-host-sync-in-hot-path(THE single bulk device->host transfer per frame — the framing contract; everything below works on host views)
        tensors = jax.device_get(list(tensors))
    # np.asarray(order="C") rather than ascontiguousarray: the latter
    # promotes 0-d arrays to 1-d, which would corrupt scalar shapes on
    # the wire. Inputs here are host values (the bulk transfer above).
    return [np.asarray(t, order="C") for t in tensors]


def encode_frame(meta: Dict[str, Any], tensors: Sequence[Any] = (),
                 path: str = "rest") -> bytes:
    """Serialize (JSON-able metadata, tensors) into one frame."""
    t0 = time.perf_counter()
    arrs = _host_tensors(list(tensors))
    if len(arrs) > MAX_TENSORS:
        raise FrameError(f"{len(arrs)} tensors exceeds the frame cap "
                         f"{MAX_TENSORS}")
    meta_b = json.dumps(meta, separators=(",", ":")).encode("utf-8")
    if len(meta_b) > MAX_META_BYTES:
        raise FrameError("frame metadata section exceeds "
                         f"{MAX_META_BYTES} bytes")
    table = bytearray()
    offset = 0
    for arr in arrs:
        name = arr.dtype.name
        code = _CODE_BY_NAME.get(name)
        if code is None:
            raise FrameError(f"dtype {name!r} has no frame encoding")
        if arr.ndim > MAX_NDIM:
            raise FrameError(f"ndim {arr.ndim} exceeds the frame cap "
                             f"{MAX_NDIM}")
        table += _ENTRY.pack(code, arr.ndim, 0, offset, arr.nbytes)
        for dim in arr.shape:
            table += _DIM.pack(dim)
        offset += arr.nbytes
    header = _HEADER.pack(MAGIC, VERSION, 0, len(arrs), len(meta_b), offset)
    buf = b"".join([header, bytes(table), meta_b]
                   + [arr.tobytes() for arr in arrs])
    _record("encode", path, len(buf), time.perf_counter() - t0)
    return buf


def decode_frame(buf: bytes, *, meta_only: bool = False,
                 path: str = "rest") -> Tuple[Dict[str, Any],
                                              List[np.ndarray]]:
    """Parse one frame back into (metadata, tensors).

    ``meta_only=True`` validates the header/table/metadata but skips
    tensor materialization and payload bounds — a frame whose payload was
    truncated or corrupted in flight still yields its metadata (the
    network handoff recovers the job id this way).

    Tensors are zero-copy views over ``buf`` (``np.frombuffer``); callers
    that outlive the buffer get their own copy implicitly when they move
    the array onto a device.
    """
    t0 = time.perf_counter()
    buf = bytes(buf) if isinstance(buf, (bytearray, memoryview)) else buf
    if not isinstance(buf, bytes):
        raise FrameError(f"frame must be bytes, got {type(buf).__name__}")
    if len(buf) < _HEADER.size:
        raise FrameError(f"truncated frame header: {len(buf)} bytes "
                         f"< {_HEADER.size}")
    magic, version, _flags, n_tensors, meta_len, payload_len = \
        _HEADER.unpack_from(buf, 0)
    if magic != MAGIC:
        raise FrameError(f"bad frame magic {magic!r}")
    if version != VERSION:
        raise FrameError(f"frame version {version} not supported "
                         f"(this build speaks {VERSION})")
    if n_tensors > MAX_TENSORS:
        raise FrameError(f"declared tensor count {n_tensors} exceeds the "
                         f"frame cap {MAX_TENSORS}")
    if meta_len > MAX_META_BYTES:
        raise FrameError(f"declared metadata length {meta_len} exceeds "
                         f"{MAX_META_BYTES} bytes")
    pos = _HEADER.size
    entries = []
    for i in range(n_tensors):
        if pos + _ENTRY.size > len(buf):
            raise FrameError(f"truncated tensor table at entry {i}")
        code, ndim, _res, offset, nbytes = _ENTRY.unpack_from(buf, pos)
        pos += _ENTRY.size
        if code >= len(_DTYPE_NAMES):
            raise FrameError(f"unknown dtype code {code} in entry {i}")
        if ndim > MAX_NDIM:
            raise FrameError(f"ndim {ndim} exceeds the frame cap "
                             f"{MAX_NDIM} in entry {i}")
        if pos + ndim * _DIM.size > len(buf):
            raise FrameError(f"truncated shape dims in entry {i}")
        shape = tuple(_DIM.unpack_from(buf, pos + k * _DIM.size)[0]
                      for k in range(ndim))
        pos += ndim * _DIM.size
        entries.append((code, shape, offset, nbytes))
    if pos + meta_len > len(buf):
        raise FrameError("truncated metadata section: declared "
                         f"{meta_len} bytes, {len(buf) - pos} remain")
    try:
        meta = json.loads(buf[pos:pos + meta_len].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise FrameError(f"frame metadata is not valid JSON: {e}")
    if not isinstance(meta, dict):
        raise FrameError("frame metadata must be a JSON object, got "
                         f"{type(meta).__name__}")
    if meta_only:
        return meta, []
    payload_start = pos + meta_len
    actual_payload = len(buf) - payload_start
    if payload_len != actual_payload:
        raise FrameError(f"payload length mismatch: header declares "
                         f"{payload_len} bytes, frame carries "
                         f"{actual_payload}")
    tensors: List[np.ndarray] = []
    for i, (code, shape, offset, nbytes) in enumerate(entries):
        # bounds BEFORE any materialization: a lying offset/nbytes must
        # never read past the buffer or allocate
        if offset + nbytes > payload_len:
            raise FrameError(f"tensor {i} spans [{offset}, "
                             f"{offset + nbytes}) past the "
                             f"{payload_len}-byte payload")
        dtype = _np_dtype(_DTYPE_NAMES[code])
        count = 1
        for dim in shape:
            count *= dim
        if count * dtype.itemsize != nbytes:
            raise FrameError(f"tensor {i} dtype/shape mismatch: shape "
                             f"{shape} x {dtype.name} needs "
                             f"{count * dtype.itemsize} bytes, entry "
                             f"declares {nbytes}")
        arr = np.frombuffer(buf, dtype=dtype, count=count,
                            offset=payload_start + offset).reshape(shape)
        tensors.append(arr)
    _record("decode", path, len(buf), time.perf_counter() - t0)
    return meta, tensors


# ---------------------------------------------------------------------------
# SeldonMessage codec: the REST/gRPC hop payload
# ---------------------------------------------------------------------------

def frameable(msg: Any) -> bool:
    """True when framing ``msg`` actually avoids a JSON tensor round-trip:
    a data message with a live array, or a binData message (whose JSON
    form pays base64). Everything else rides JSON unchanged."""
    if not isinstance(msg, SeldonMessage):
        return False
    if msg.which == "data" and msg.data is not None \
            and msg.data.array is not None:
        return getattr(msg.data.array, "dtype", np.dtype(object)) != object \
            and np.asarray(msg.data.array).dtype.name in _CODE_BY_NAME
    return msg.which == "binData"


def encode_message(msg: SeldonMessage, path: str = "rest") -> bytes:
    """One SeldonMessage as a frame. The message's JSON shape minus the
    tensor values rides the metadata section; the array (or binData
    bytes) rides raw."""
    meta: Dict[str, Any] = {"kind": "SeldonMessage", "which": msg.which}
    if msg.status is not None:
        meta["status"] = msg.status.to_dict()
    meta["meta"] = msg.meta.to_dict()
    tensors: List[Any] = []
    if msg.which == "data" and msg.data is not None \
            and msg.data.array is not None:
        meta["data"] = {"names": list(msg.data.names),
                        "encoding": msg.data.encoding, "tensorRef": 0}
        tensors = [msg.data.array]
    elif msg.which == "binData":
        meta["binDataRef"] = 0
        tensors = [np.frombuffer(msg.bin_data or b"", dtype=np.uint8)]
    elif msg.which == "data" and msg.data is not None:
        # object ndarray (raw nested lists): no raw-buffer form — the
        # JSON dict rides the metadata section whole
        meta["data"] = msg.data.to_dict()
    elif msg.which == "strData":
        meta["strData"] = msg.str_data
    elif msg.which == "jsonData":
        meta["jsonData"] = msg.json_data
    return encode_frame(meta, tensors, path=path)


def decode_message(buf: bytes, path: str = "rest") -> SeldonMessage:
    meta, tensors = decode_frame(buf, path=path)
    if meta.get("kind") != "SeldonMessage":
        raise FrameError("frame does not carry a SeldonMessage "
                         f"(kind={meta.get('kind')!r})")
    msg = SeldonMessage(
        status=Status.from_dict(meta["status"]) if "status" in meta
        else None,
        meta=Meta.from_dict(meta.get("meta")),
    )
    which = meta.get("which", "")
    d = meta.get("data")
    if isinstance(d, dict) and "tensorRef" in d:
        ref = d["tensorRef"]
        if not isinstance(ref, int) or not 0 <= ref < len(tensors):
            raise FrameError(f"tensorRef {ref!r} out of range for "
                             f"{len(tensors)} tensors")
        msg.data = DefaultData(names=list(d.get("names", []) or []),
                               array=tensors[ref],
                               encoding=d.get("encoding", "tensor"))
        msg.which = "data"
    elif isinstance(d, dict):
        msg.data = DefaultData.from_dict(d)
        msg.which = "data"
    elif "binDataRef" in meta:
        ref = meta["binDataRef"]
        if not isinstance(ref, int) or not 0 <= ref < len(tensors):
            raise FrameError(f"binDataRef {ref!r} out of range for "
                             f"{len(tensors)} tensors")
        msg.bin_data = tensors[ref].tobytes()
        msg.which = "binData"
    elif "strData" in meta:
        msg.str_data = meta["strData"]
        msg.which = "strData"
    elif "jsonData" in meta:
        msg.json_data = meta["jsonData"]
        msg.which = "jsonData"
    if which and msg.which and which != msg.which:
        raise FrameError(f"frame declares which={which!r} but carries "
                         f"{msg.which!r}")
    return msg


# ---------------------------------------------------------------------------
# gRPC mirror: a frame rides the proto binData arm (raw bytes on the wire —
# proto binData never base64s), tagged via meta so the server can tell a
# frame envelope from user binData
# ---------------------------------------------------------------------------

FRAME_TAG = "content-type"


def grpc_wrap(msg: SeldonMessage) -> SeldonMessage:
    """Envelope a message as frame-bytes-in-binData for a gRPC hop."""
    return SeldonMessage.from_bytes(
        encode_message(msg, path="grpc"),
        meta=Meta(tags={FRAME_TAG: CONTENT_TYPE_FRAME}))


def grpc_is_framed(msg: Any) -> bool:
    return (isinstance(msg, SeldonMessage) and msg.which == "binData"
            and msg.meta.tags.get(FRAME_TAG) == CONTENT_TYPE_FRAME)


def grpc_unwrap(msg: SeldonMessage) -> SeldonMessage:
    return decode_message(msg.bin_data or b"", path="grpc")


# ---------------------------------------------------------------------------
# pytree skeleton: JSON-able structure encoding for the KV-handoff frames
# (runtime/disagg.py NetworkHandoff). Treedefs are not JSON-serializable
# and unpickling from a socket is not acceptable in a frame decoder, so
# the standard containers are encoded explicitly.
# ---------------------------------------------------------------------------

def tree_skeleton(tree: Any) -> Tuple[Dict[str, Any], List[Any]]:
    """(JSON-able skeleton, leaves) for a pytree of dict/list/tuple
    containers. Leaves are replaced by their index into the leaf list."""
    leaves: List[Any] = []

    def enc(x: Any) -> Dict[str, Any]:
        if isinstance(x, tuple):
            return {"T": "tuple", "items": [enc(i) for i in x]}
        if isinstance(x, list):
            return {"T": "list", "items": [enc(i) for i in x]}
        if isinstance(x, dict):
            keys = list(x.keys())
            if not all(isinstance(k, str) for k in keys):
                raise FrameError("tree skeleton requires string dict keys")
            return {"T": "dict", "keys": keys,
                    "items": [enc(x[k]) for k in keys]}
        leaves.append(x)
        return {"T": "leaf", "i": len(leaves) - 1}

    return enc(tree), leaves


def tree_unskeleton(skel: Any, leaves: Sequence[Any]) -> Any:
    """Rebuild the pytree from ``tree_skeleton`` output. Malformed
    skeletons raise FrameError (the network handoff treats that like any
    other corrupt frame)."""

    def dec(s: Any) -> Any:
        if not isinstance(s, dict) or "T" not in s:
            raise FrameError("malformed tree skeleton node")
        t = s["T"]
        if t == "leaf":
            i = s.get("i")
            if not isinstance(i, int) or not 0 <= i < len(leaves):
                raise FrameError(f"tree skeleton leaf {i!r} out of range")
            return leaves[i]
        if t == "tuple":
            return tuple(dec(i) for i in s.get("items", []))
        if t == "list":
            return [dec(i) for i in s.get("items", [])]
        if t == "dict":
            keys = s.get("keys", [])
            items = s.get("items", [])
            if len(keys) != len(items):
                raise FrameError("tree skeleton dict keys/items mismatch")
            return {k: dec(v) for k, v in zip(keys, items)}
        raise FrameError(f"unknown tree skeleton node type {t!r}")

    return dec(skel)
