from seldon_core_tpu.codec.response import construct_response
from seldon_core_tpu.codec.staging import stage_to_device

__all__ = ["construct_response", "stage_to_device"]
