"""Device staging: move request payloads onto TPU as XLA buffers.

The reference keeps tensors as numpy between every hop and re-serializes per
node (`python/seldon_core/utils.py:147-278`). Here, ingress decodes once and
stages the array on device; graph nodes that are JAX computations consume the
device buffer directly. Shape bucketing keeps XLA from recompiling per request
size: batch dims are padded up to the next bucket so a small, fixed set of
compiled programs serves all traffic.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

DEFAULT_BUCKETS: Tuple[int, ...] = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


def bucket_size(n: int, buckets: Sequence[int] = DEFAULT_BUCKETS) -> int:
    """Smallest bucket >= n; grows by doubling past the last bucket."""
    for b in buckets:
        if n <= b:
            return b
    b = buckets[-1]
    while b < n:
        b *= 2
    return b


def pad_batch(arr: np.ndarray, buckets: Sequence[int] = DEFAULT_BUCKETS) -> Tuple[np.ndarray, int]:
    """Pad the leading (batch) dim up to its bucket. Returns (padded, true_n)."""
    n = arr.shape[0] if arr.ndim else 1
    target = bucket_size(n, buckets)
    if target == n:
        return arr, n
    pad_width = [(0, target - n)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad_width), n


def stage_to_device(
    arr: np.ndarray,
    dtype: Optional[np.dtype] = None,
    device=None,
    sharding=None,
    pad: bool = False,
    buckets: Sequence[int] = DEFAULT_BUCKETS,
):
    """Decode-once device staging: numpy -> jax.Array on TPU (or given sharding).

    Returns (device_array, true_batch). With ``pad=True`` the leading dim is
    bucketed so downstream jitted fns hit the compile cache.
    """
    import jax

    true_n = arr.shape[0] if arr.ndim else 1
    if pad:
        arr, true_n = pad_batch(arr, buckets)
    if dtype is not None:
        arr = arr.astype(dtype, copy=False)
    if sharding is not None:
        return jax.device_put(arr, sharding), true_n
    if device is not None:
        return jax.device_put(arr, device), true_n
    return jax.device_put(arr), true_n
