"""Response construction: turn a component's raw return value into a
SeldonMessage, mirroring the reference's type rules
(`python/seldon_core/utils.py:410-469`):

- array/list result: encode following the request's DefaultData encoding when
  numeric (tensor->tensor, ndarray->ndarray), else ndarray; if the request was
  not DefaultData, numeric results become tensor, non-numeric ndarray.
- str -> strData, bytes -> binData, dict -> jsonData.
- names: feature_names() on the request flow, class_names() on the response
  flow (default "t:i" for 2-D numeric outputs).
- meta carries puid from the request plus component tags() and metrics().
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np

from seldon_core_tpu.components.component import (
    client_class_names,
    client_custom_metrics,
    client_custom_tags,
    client_feature_names,
)
from seldon_core_tpu.contracts.payload import (
    ENC_NDARRAY,
    ENC_TENSOR,
    DefaultData,
    Meta,
    Metric,
    SeldonError,
    SeldonMessage,
)


def _is_jax_array(x: Any) -> bool:
    # Avoid importing jax at module load in pure-CPU paths.
    return type(x).__module__.startswith(("jaxlib", "jax"))


def response_meta(component: Any, request_meta: Optional[Meta]) -> Meta:
    meta = Meta()
    if request_meta is not None and request_meta.puid:
        meta.puid = request_meta.puid
    tags = client_custom_tags(component)
    if tags:
        meta.tags.update(tags)
    for m in client_custom_metrics(component):
        meta.metrics.append(Metric.from_dict(m))
    return meta


def construct_response(
    component: Any,
    is_request: bool,
    request: Optional[SeldonMessage],
    raw_result: Any,
) -> SeldonMessage:
    """Build the response SeldonMessage from a component's raw return value."""
    if isinstance(raw_result, SeldonMessage):
        if not raw_result.meta.puid and request is not None and request.meta.puid:
            raw_result.meta.puid = request.meta.puid
        return raw_result

    meta = response_meta(component, request.meta if request is not None else None)

    if isinstance(raw_result, (bytes, bytearray)):
        return SeldonMessage(meta=meta, bin_data=bytes(raw_result), which="binData")
    if isinstance(raw_result, str):
        return SeldonMessage(meta=meta, str_data=raw_result, which="strData")
    if isinstance(raw_result, dict):
        return SeldonMessage(meta=meta, json_data=raw_result, which="jsonData")

    if _is_jax_array(raw_result):
        arr = np.asarray(raw_result)
    elif isinstance(raw_result, np.ndarray):
        arr = raw_result
    elif isinstance(raw_result, (list, tuple)):
        arr = np.asarray(raw_result)
    elif np.isscalar(raw_result):
        arr = np.asarray(raw_result)
    else:
        raise SeldonError(
            f"Unknown data type returned as payload (must be array, list, str, bytes or dict): "
            f"{type(raw_result).__name__}"
        )

    numeric = np.issubdtype(arr.dtype, np.number) or arr.dtype == np.bool_
    if request is not None and request.which == "data" and request.data is not None:
        encoding = request.data.encoding if numeric else ENC_NDARRAY
    else:
        encoding = ENC_TENSOR if numeric else ENC_NDARRAY

    if is_request:
        req_names: Sequence[str] = request.names if request is not None else []
        names = client_feature_names(component, req_names)
    else:
        names = client_class_names(component, arr)

    data = DefaultData(names=names, array=arr if numeric else None, encoding=encoding)
    if not numeric:
        data.raw_ndarray = arr.tolist()
    return SeldonMessage(meta=meta, data=data, which="data")
