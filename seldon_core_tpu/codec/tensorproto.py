"""Hand-rolled TensorProto / PredictRequest wire codec for the TF-Serving
proxy (servers/tfproxy.py).

These are pure HOST payload converters — protobuf bytes in, numpy out, no
device values anywhere — which is exactly why they live in ``codec/`` and
not in ``servers/``: the graftlint host-sync heuristic treats ``servers/``
as a hot-path package and (rightly) flags every ``np.asarray`` in
decode/predict-named functions there. Keeping wire codecs next to the
other payload codecs (codec/staging.py) makes the package boundary carry
the "no device values here" claim instead of a baseline entry per call
site (PR 5 graftlint baseline burn-down).

No tensorflow / tensorflow-serving-api import — the frames are encoded and
decoded by hand against tensorflow/core/framework/types.proto semantics,
so heterogeneous graphs can reach an external TF-Serving without dragging
the TF runtime into the image.
"""

from __future__ import annotations

import struct
from typing import Dict

import numpy as np

from seldon_core_tpu.contracts.payload import SeldonError

# TensorProto dtype enum values (tensorflow/core/framework/types.proto)
_DT_FLOAT = 1
_DT_DOUBLE = 2
_DT_INT32 = 3
_DT_INT64 = 9


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _len_delim(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def encode_predict_request(arr: np.ndarray, model_name: str, signature_name: str,
                           input_name: str) -> bytes:
    """tensorflow.serving.PredictRequest wire bytes: model_spec{name,
    signature_name} + inputs[input_name] = TensorProto(dtype, shape,
    float_val/double_val packed)."""
    arr = np.asarray(arr)
    flat = arr.reshape(-1)
    if arr.dtype == np.float64:
        dtype, val_field = _DT_DOUBLE, 6
        packed = struct.pack("<%dd" % flat.size, *flat.tolist())
    elif np.issubdtype(arr.dtype, np.integer):
        # int inputs stay ints on the wire (token-id models): int32 ->
        # int_val (7), anything wider -> int64_val (10); protobuf varints
        # encode negatives as 10-byte two's complement
        if arr.dtype.itemsize <= 4 and arr.dtype != np.uint32:
            dtype, val_field = _DT_INT32, 7
        else:
            dtype, val_field = _DT_INT64, 10
        packed = b"".join(
            _varint(int(v) & 0xFFFFFFFFFFFFFFFF) for v in flat.tolist())
    else:
        arr = arr.astype(np.float32)
        flat = arr.reshape(-1)
        dtype, val_field = _DT_FLOAT, 5
        packed = struct.pack("<%df" % flat.size, *flat.tolist())
    # TensorShapeProto: repeated Dim dim = 2; Dim.size = 1 (int64)
    shape = b"".join(_len_delim(2, _tag(1, 0) + _varint(d)) for d in arr.shape)
    tensor = (
        _tag(1, 0) + _varint(dtype)
        + _len_delim(2, shape)
        + _len_delim(val_field, packed)
    )
    model_spec = (
        _len_delim(1, model_name.encode())
        + _len_delim(3, signature_name.encode())
    )
    entry = _len_delim(1, input_name.encode()) + _len_delim(2, tensor)
    return _len_delim(1, model_spec) + _len_delim(2, entry)


def _read_varint(buf: bytes, off: int):
    shift = 0
    val = 0
    while True:
        b = buf[off]
        off += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, off
        shift += 7


def _iter_fields(buf: bytes):
    off = 0
    while off < len(buf):
        key, off = _read_varint(buf, off)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, off = _read_varint(buf, off)
        elif wire == 2:
            ln, off = _read_varint(buf, off)
            val = buf[off:off + ln]
            off += ln
        elif wire == 5:
            val = buf[off:off + 4]
            off += 4
        elif wire == 1:
            val = buf[off:off + 8]
            off += 8
        else:
            raise SeldonError(f"unsupported protobuf wire type {wire}")
        yield field, wire, val


def _signed64(v: int) -> int:
    """Protobuf varints carry negatives as 64-bit two's complement."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _varint_list(val, wire) -> list:
    """Decode an int_val/int64_val field occurrence: packed (wire 2) holds
    back-to-back varints; unpacked (wire 0) is a single value."""
    if wire == 0:
        return [_signed64(val)]
    out = []
    off = 0
    while off < len(val):
        v, off = _read_varint(val, off)
        out.append(_signed64(v))
    return out


def decode_tensor_proto(buf: bytes) -> np.ndarray:
    dtype = _DT_FLOAT
    dims = []
    floats: list = []
    doubles: list = []
    ints: list = []
    for field, wire, val in _iter_fields(buf):
        if field == 1 and wire == 0:
            dtype = val
        elif field == 2 and wire == 2:  # tensor_shape
            for f2, w2, v2 in _iter_fields(val):
                if f2 == 2 and w2 == 2:  # Dim
                    for f3, w3, v3 in _iter_fields(v2):
                        if f3 == 1 and w3 == 0:
                            dims.append(v3)
        elif field == 5:  # float_val (packed or repeated)
            if wire == 2:
                floats.extend(struct.unpack(f"<{len(val) // 4}f", val))
            else:
                floats.append(struct.unpack("<f", val)[0])
        elif field == 6:  # double_val
            if wire == 2:
                doubles.extend(struct.unpack(f"<{len(val) // 8}d", val))
            else:
                doubles.append(struct.unpack("<d", val)[0])
        elif field == 7:  # int_val (DT_INT32 and narrower)
            ints.extend(_varint_list(val, wire))
        elif field == 10:  # int64_val
            ints.extend(_varint_list(val, wire))
    if dtype == _DT_DOUBLE:
        arr = np.asarray(doubles, dtype=np.float64)
    elif dtype == _DT_FLOAT:
        arr = np.asarray(floats, dtype=np.float32)
    elif dtype == _DT_INT32:
        arr = np.asarray(ints, dtype=np.int32)
    elif dtype == _DT_INT64:
        arr = np.asarray(ints, dtype=np.int64)
    else:
        raise SeldonError(
            f"TF-Serving returned TensorProto dtype {dtype}, which this proxy "
            "does not decode (supported: DT_FLOAT/DT_DOUBLE/DT_INT32/DT_INT64)",
            status_code=502, reason="UPSTREAM_ERROR")
    if dims and int(np.prod(dims)) == arr.size:
        arr = arr.reshape(dims)
    return arr


def decode_predict_response(buf: bytes, output_name: str) -> np.ndarray:
    """tensorflow.serving.PredictResponse: outputs map (field 1); returns the
    named output, or the single output when only one is present."""
    outputs: Dict[str, np.ndarray] = {}
    for field, wire, val in _iter_fields(buf):
        if field == 1 and wire == 2:
            key = ""
            tensor = b""
            for f2, w2, v2 in _iter_fields(val):
                if f2 == 1 and w2 == 2:
                    key = v2.decode()
                elif f2 == 2 and w2 == 2:
                    tensor = v2
            outputs[key] = decode_tensor_proto(tensor)
    if output_name in outputs:
        return outputs[output_name]
    if len(outputs) == 1:
        return next(iter(outputs.values()))
    raise SeldonError(
        f"TF-Serving response missing output {output_name!r} "
        f"(has {sorted(outputs)})", status_code=502, reason="UPSTREAM_ERROR")
