"""Multi-armed-bandit ROUTER components.

Capability parity with the reference's analytics routers
(`components/routers/epsilon-greedy/EpsilonGreedy.py:9-136` and
`components/routers/thompson-sampling/ThompsonSampling.py`): stateful graph
nodes that choose a child branch per request and learn from the feedback
replay path (`Feedback.reward` routed back down the branch that served the
original request — SURVEY.md §3.5).

State is plain numpy so instances pickle cleanly through
``runtime.persistence`` (the reference persists bandit posteriors to Redis;
here the StateStore does the same job). Engine-side the per-branch reward
counters also surface as Prometheus metrics via ``metrics()``.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from seldon_core_tpu.components.component import SeldonComponent
from seldon_core_tpu.components.metrics import create_counter, create_gauge


class _BanditRouter(SeldonComponent):
    """Shared bookkeeping: per-branch pull counts and reward sums, a lock
    (feedback and route arrive concurrently), and metrics/tags exposure."""

    def __init__(self, n_branches: int = 2, seed: Optional[int] = None, **kwargs: Any):
        super().__init__(**kwargs)
        self.n_branches = int(n_branches)
        if self.n_branches < 1:
            raise ValueError(f"n_branches must be >= 1, got {n_branches}")
        self.pulls = np.zeros(self.n_branches, dtype=np.int64)
        self.reward_sum = np.zeros(self.n_branches, dtype=np.float64)
        self.fail_sum = np.zeros(self.n_branches, dtype=np.float64)
        # Peer replicas' contributions (multi-replica DP serving): this
        # replica's feedback lands in the local arrays above; ReplicaSync
        # periodically publishes the local counts and refreshes these sums
        # of the other replicas' counts — a G-counter, so no CAS and no
        # double counting. Decisions read local + peers.
        self.peer_pulls = np.zeros(self.n_branches, dtype=np.int64)
        self.peer_reward_sum = np.zeros(self.n_branches, dtype=np.float64)
        self.peer_fail_sum = np.zeros(self.n_branches, dtype=np.float64)
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()
        self._last_branch: Optional[int] = None

    # pickling: locks are not picklable; rebuild on restore.
    def __getstate__(self):
        state = dict(self.__dict__)
        state.pop("_lock", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()
        # snapshots from before multi-replica sync lack the peer arrays
        for name in ("peer_pulls", "peer_reward_sum", "peer_fail_sum"):
            if name not in self.__dict__:
                dtype = np.int64 if name == "peer_pulls" else np.float64
                setattr(self, name, np.zeros(self.n_branches, dtype=dtype))

    def send_feedback(
        self,
        features: np.ndarray,
        feature_names: Sequence[str],
        reward: float,
        truth: Optional[np.ndarray],
        routing: Optional[int] = None,
    ) -> None:
        if routing is None or not (0 <= int(routing) < self.n_branches):
            return
        branch = int(routing)
        reward = float(reward)
        with self._lock:
            self.pulls[branch] += 1
            # Rewards are interpreted as success fractions in [0, 1], the
            # reference's convention for its bandit case study.
            r = min(max(reward, 0.0), 1.0)
            self.reward_sum[branch] += r
            self.fail_sum[branch] += 1.0 - r

    # ------------------------------------------------------- replica sync
    def stats_snapshot(self) -> Dict[str, Any]:
        """This replica's own accumulated statistics (not the peers')."""
        with self._lock:
            return {
                "pulls": self.pulls.copy(),
                "reward_sum": self.reward_sum.copy(),
                "fail_sum": self.fail_sum.copy(),
            }

    def reset_local_stats(self) -> None:
        """Zero this replica's own counters (used when a fresh replica booted
        from a shared-key snapshot: those counts belong to another replica
        and must not be republished under this replica's key)."""
        with self._lock:
            self.pulls = np.zeros(self.n_branches, dtype=np.int64)
            self.reward_sum = np.zeros(self.n_branches, dtype=np.float64)
            self.fail_sum = np.zeros(self.n_branches, dtype=np.float64)

    def _valid_snapshot(self, s: Dict[str, Any]) -> bool:
        try:
            return all(
                np.asarray(s[k]).shape == (self.n_branches,)
                for k in ("pulls", "reward_sum", "fail_sum")
            )
        except (KeyError, TypeError):
            return False

    def load_stats_snapshot(self, s: Dict[str, Any]) -> bool:
        """Install a snapshot as this replica's own counters (boot resume).
        Rejects snapshots whose shape doesn't match n_branches (e.g. the
        router was redeployed with a different branch count)."""
        if not self._valid_snapshot(s):
            return False
        with self._lock:
            self.pulls = np.asarray(s["pulls"], dtype=np.int64).copy()
            self.reward_sum = np.asarray(s["reward_sum"], dtype=np.float64).copy()
            self.fail_sum = np.asarray(s["fail_sum"], dtype=np.float64).copy()
        return True

    def apply_peer_stats(self, snapshots: Sequence[Dict[str, Any]]) -> None:
        """Replace the peer contribution with the sum of the given replica
        snapshots (each the ``stats_snapshot()`` of one other replica).
        Mis-shaped snapshots (stale keys from an older branch count) are
        skipped rather than poisoning the arrays."""
        pulls = np.zeros(self.n_branches, dtype=np.int64)
        reward = np.zeros(self.n_branches, dtype=np.float64)
        fail = np.zeros(self.n_branches, dtype=np.float64)
        for s in snapshots:
            if not self._valid_snapshot(s):
                continue
            pulls += np.asarray(s["pulls"], dtype=np.int64)
            reward += np.asarray(s["reward_sum"], dtype=np.float64)
            fail += np.asarray(s["fail_sum"], dtype=np.float64)
        with self._lock:
            self.peer_pulls = pulls
            self.peer_reward_sum = reward
            self.peer_fail_sum = fail

    def _totals(self):
        """Combined (local + peer) stats; callers hold the lock."""
        return (
            self.pulls + self.peer_pulls,
            self.reward_sum + self.peer_reward_sum,
            self.fail_sum + self.peer_fail_sum,
        )

    def branch_means(self) -> np.ndarray:
        with self._lock:
            pulls, reward, _ = self._totals()
            return reward / np.maximum(pulls, 1)

    def tags(self) -> Dict[str, Any]:
        return {
            "bandit": type(self).__name__,
            "branch_means": [round(float(m), 6) for m in self.branch_means()],
        }

    def metrics(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        with self._lock:
            # consume the route marker so the counter ticks once per route,
            # not once per metrics collection (feedback also collects)
            branch, self._last_branch = self._last_branch, None
        if branch is not None:
            out.append(create_counter(f"bandit_route_branch_{branch}", 1.0))
        for i, m in enumerate(self.branch_means()):
            out.append(create_gauge(f"bandit_branch_{i}_mean_reward", float(m)))
        return out


class EpsilonGreedy(_BanditRouter):
    """ε-greedy: with probability ``epsilon`` explore a uniform random branch,
    otherwise exploit the branch with the highest mean reward
    (`EpsilonGreedy.py:9-136`)."""

    def __init__(
        self,
        n_branches: int = 2,
        epsilon: float = 0.1,
        seed: Optional[int] = None,
        best_branch: int = 0,
        **kwargs: Any,
    ):
        super().__init__(n_branches=n_branches, seed=seed, **kwargs)
        if not 0.0 <= float(epsilon) <= 1.0:
            raise ValueError(f"epsilon must be in [0,1], got {epsilon}")
        self.epsilon = float(epsilon)
        # starting exploit choice before any feedback (reference's
        # `best_branch` init param)
        if not 0 <= int(best_branch) < self.n_branches:
            raise ValueError(f"best_branch {best_branch} out of range for {self.n_branches} branches")
        self.best_branch = int(best_branch)

    def route(self, X: np.ndarray, names: Sequence[str]) -> int:
        with self._lock:
            pulls, reward, _ = self._totals()
            if self._rng.random() < self.epsilon:
                branch = int(self._rng.integers(self.n_branches))
            elif pulls.sum() == 0:
                branch = self.best_branch
            else:
                means = reward / np.maximum(pulls, 1)
                branch = int(np.argmax(means))
            self._last_branch = branch
            return branch


class ThompsonSampling(_BanditRouter):
    """Thompson sampling with Beta posteriors per branch
    (`ThompsonSampling.py`): route samples θ_i ~ Beta(α_i, β_i) and picks
    argmax; feedback adds reward/failure mass to the routed branch's
    posterior."""

    def __init__(
        self,
        n_branches: int = 2,
        alpha: float = 1.0,
        beta: float = 1.0,
        seed: Optional[int] = None,
        **kwargs: Any,
    ):
        super().__init__(n_branches=n_branches, seed=seed, **kwargs)
        if alpha <= 0 or beta <= 0:
            raise ValueError("alpha and beta priors must be positive")
        self.alpha0 = float(alpha)
        self.beta0 = float(beta)

    def route(self, X: np.ndarray, names: Sequence[str]) -> int:
        with self._lock:
            _, reward, fail = self._totals()
            a = self.alpha0 + reward
            b = self.beta0 + fail
            theta = self._rng.beta(a, b)
            branch = int(np.argmax(theta))
            self._last_branch = branch
            return branch
