"""Analytics graph components: bandit routers and outlier-detection
transformers (capability of the reference's `components/routers/` and
`components/outlier-detection/` trees, rebuilt JAX-native)."""

from seldon_core_tpu.analytics.routers import EpsilonGreedy, ThompsonSampling
from seldon_core_tpu.analytics.canary import CanaryRouter, ShadowNode
from seldon_core_tpu.analytics.explainers import SaliencyExplainer
from seldon_core_tpu.analytics.outliers import (
    MahalanobisOutlierDetector,
    IsolationForestOutlierDetector,
    Seq2SeqOutlierDetector,
    VAEOutlierDetector,
)

__all__ = [
    "CanaryRouter",
    "EpsilonGreedy",
    "SaliencyExplainer",
    "ShadowNode",
    "ThompsonSampling",
    "MahalanobisOutlierDetector",
    "IsolationForestOutlierDetector",
    "Seq2SeqOutlierDetector",
    "VAEOutlierDetector",
]
