"""Graph-level canary and shadow traffic: progressive-rollout components.

The reference's rollout story is Istio/Ambassador traffic splits between
predictor versions plus its bandit routers (SURVEY.md §3.5); this module is
the in-process version with the piece the reference leaves to humans —
AUTOMATIC rollback — built in:

- :class:`CanaryRouter` — a ROUTER over ``[baseline, candidate]`` that
  sends a deterministic ``fraction`` of live traffic to the candidate and
  compares the two branches' TTFT/latency and error rate.  The latency
  comparison runs through the analytics outlier machinery
  (:class:`~seldon_core_tpu.analytics.outliers.MahalanobisOutlierDetector`
  — baseline observations stream into its running statistics, candidate
  windows are scored against them), so "degraded" means *statistically
  outlying vs the baseline's own distribution*, not a hand-tuned absolute
  threshold.  On degradation the router ROLLS BACK: all subsequent
  traffic routes to baseline, in-flight candidate requests complete
  normally — the rollback itself can never fail a client request
  (tests/test_canary.py).  Reward plumbing is shared with the bandit
  routers (:class:`~seldon_core_tpu.analytics.routers._BanditRouter`
  ``send_feedback``), so the engine's feedback replay path needs nothing
  new.
- :class:`ShadowNode` — wraps a primary component and MIRRORS a
  deterministic fraction of requests to a shadow candidate whose
  responses are discarded; it records output divergence and latency
  deltas instead.  Shadow failures are recorded, never raised: the
  shadow can crash forever and the client never notices.

Determinism discipline (docs/control-plane.md): the traffic split is a
pure function of the request counter (no RNG), latency observations come
from the engine's INJECTABLE clock (`GraphEngine` times every routed
branch on ``resilience.clock`` and feeds ``observe_outcome``), and the
rollback decision is a pure function of the two observation windows — so
the whole warmup -> canary -> rollback cycle replays exactly under
``testing.faults.FaultClock``.
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from seldon_core_tpu.analytics.routers import _BanditRouter
from seldon_core_tpu.components.component import SeldonComponent

logger = logging.getLogger(__name__)

BASELINE = 0
CANDIDATE = 1

# rollout phases (CanaryRouter.phase)
CANARY = "canary"            # splitting traffic, evaluating
PROMOTED = "promoted"        # candidate won: it takes all traffic
ROLLED_BACK = "rolled_back"  # candidate degraded: baseline takes all

_PHASE_CODES = {CANARY: 0, PROMOTED: 1, ROLLED_BACK: 2}


def canary_split(n: int, fraction: float) -> int:
    """The deterministic traffic split: request number ``n`` (0-based)
    goes to the candidate iff it crosses the next ``fraction`` boundary —
    ``int((n+1)*f) > int(n*f)``.  Over any window the candidate share is
    within one request of ``fraction``, with no RNG: the same request
    sequence always splits the same way (the property every replayed
    rollout test rests on)."""
    if fraction <= 0.0:
        return BASELINE
    if fraction >= 1.0:
        return CANDIDATE
    return CANDIDATE if int((n + 1) * fraction) > int(n * fraction) \
        else BASELINE


def evaluate_canary(
    baseline_rows: Sequence[float],
    candidate_rows: Sequence[float],
    baseline_errors: Sequence[int],
    candidate_errors: Sequence[int],
    detector: Any,
    *,
    min_samples: int,
    outlier_fraction: float,
    max_error_rate_excess: float,
) -> Optional[str]:
    """The PURE rollback decision over two observation windows.  Returns a
    degradation reason, or None when the candidate holds.  ``detector``
    is the Mahalanobis scorer whose running statistics the baseline rows
    have already been folded into; candidate latencies are scored against
    them WITHOUT folding (``score_frozen``) — a sustained degradation
    must not shift the reference distribution toward itself — and the
    candidate is latency-degraded when more than ``outlier_fraction`` of
    its window scores past the detector's threshold.  Error-rate
    degradation is a straight excess comparison of window means."""
    # one engine observation lands in BOTH windows (latency + error), so
    # the sample floor is the larger window per branch, not the sum
    if (max(len(candidate_rows), len(candidate_errors)) < min_samples
            or max(len(baseline_rows), len(baseline_errors)) < min_samples):
        return None
    if candidate_errors or baseline_errors:
        base_err = float(np.mean(baseline_errors)) if baseline_errors else 0.0
        cand_err = float(np.mean(candidate_errors)) if candidate_errors \
            else 0.0
        if cand_err - base_err > max_error_rate_excess:
            return (f"error rate {cand_err:.2f} exceeds baseline "
                    f"{base_err:.2f} by > {max_error_rate_excess:.2f}")
    if candidate_rows:
        scores = detector.score_frozen(
            np.asarray(candidate_rows, dtype=np.float64)[:, None])
        frac = float(np.mean(scores > detector.threshold))
        if frac > outlier_fraction:
            return (f"{frac:.2f} of candidate latencies are outliers vs "
                    f"the baseline distribution (threshold "
                    f"{detector.threshold})")
    return None


class CanaryRouter(_BanditRouter):
    """ROUTER over ``[baseline, candidate]`` with automatic rollback.

    Observations arrive through two existing paths, neither new to the
    engine: the routed-branch outcome hook (``observe_outcome`` — the
    engine times every routed request's subtree on its injectable clock)
    and the feedback replay path (``send_feedback`` — shared with the
    bandit routers; rewards < 0.5 count as errors).  Every
    ``eval_every`` candidate observations the rollback decision runs
    (:func:`evaluate_canary`); a degraded candidate flips the phase to
    ``rolled_back`` and all later traffic routes to baseline.  A
    candidate that survives ``promote_after`` evaluations is PROMOTED
    (0 = stay in canary until told).

    All mutable state lives under the inherited ``_lock`` (route,
    observe, feedback and the /metrics scrape race); the Mahalanobis
    detector holds its own lock and is only ever called with ours held
    — a one-way lock order with no reverse edge."""

    def __init__(
        self,
        fraction: float = 0.1,
        window: int = 64,
        min_samples: int = 8,
        eval_every: int = 8,
        outlier_threshold: float = 3.0,
        outlier_fraction: float = 0.5,
        max_error_rate_excess: float = 0.2,
        promote_after: int = 0,
        seed: Optional[int] = None,
        **kwargs: Any,
    ):
        super().__init__(n_branches=2, seed=seed, **kwargs)
        if not 0.0 <= float(fraction) <= 1.0:
            raise ValueError(f"fraction must be in [0,1], got {fraction}")
        from seldon_core_tpu.analytics.outliers import (
            MahalanobisOutlierDetector)

        self.fraction = float(fraction)
        self.window = int(window)
        self.min_samples = int(min_samples)
        self.eval_every = max(int(eval_every), 1)
        self.outlier_fraction = float(outlier_fraction)
        self.max_error_rate_excess = float(max_error_rate_excess)
        self.promote_after = int(promote_after)
        self.phase = CANARY
        self.rollback_reason = ""
        self._routed = 0
        self._lat: List[Any] = [deque(maxlen=self.window) for _ in range(2)]
        self._err: List[Any] = [deque(maxlen=self.window) for _ in range(2)]
        # baseline rows not yet folded into the detector's running stats —
        # BOUNDED (evaluations drain it, but a terminal-phase router never
        # evaluates again, and an unbounded buffer would grow one float
        # per baseline request for the router's lifetime)
        self._baseline_unfolded: Any = deque(maxlen=max(4 * self.window, 256))
        self._since_eval = 0
        self.evaluations_total = 0
        self.rollbacks_total = 0
        self._detector = MahalanobisOutlierDetector(
            threshold=outlier_threshold)
        # readiness-time prewarm: the first score() jit-compiles the
        # Mahalanobis step + row buckets (seconds) — paying that inside
        # _evaluate_locked would park the engine's serving thread under
        # the router lock; compile now, then zero the dummy row back out
        self._detector.score(np.zeros((1, 1)))
        self._detector.reset_stats()

    # -- routing ---------------------------------------------------------
    def route(self, X: np.ndarray, names: Sequence[str]) -> int:
        with self._lock:
            if self.phase == ROLLED_BACK:
                branch = BASELINE
            elif self.phase == PROMOTED:
                branch = CANDIDATE
            else:
                branch = canary_split(self._routed, self.fraction)
                self._routed += 1
            self._last_branch = branch
            return branch

    # -- observations ----------------------------------------------------
    def observe_outcome(self, branch: int, latency_s: float,
                        error: bool = False) -> None:
        """The engine's routed-branch hook: one (latency, error) sample on
        the engine's injectable clock.  Also callable directly by a
        serving harness feeding per-branch TTFT quantiles."""
        if branch not in (BASELINE, CANDIDATE):
            return
        with self._lock:
            self._lat[branch].append(float(latency_s))
            self._err[branch].append(1 if error else 0)
            if branch == BASELINE and not error and self.phase == CANARY:
                # only healthy baseline latencies define "normal" — and
                # only while there is still a decision to make: a
                # promoted/rolled-back router never evaluates again, so
                # accumulating for it would be a pure leak
                self._baseline_unfolded.append(float(latency_s))
            if branch == CANDIDATE and self.phase == CANARY:
                self._since_eval += 1
                if self._since_eval >= self.eval_every:
                    self._since_eval = 0
                    self._evaluate_locked()

    def send_feedback(self, features, feature_names, reward, truth,
                      routing: Optional[int] = None) -> None:
        """Shared bandit reward path (satellite regression:
        tests/test_analytics.py proves feedback shifts bandit routing
        mass end-to-end) plus the canary's error signal: reward < 0.5
        counts as a candidate/baseline error sample."""
        super().send_feedback(features, feature_names, reward, truth,
                              routing=routing)
        if routing is None or int(routing) not in (BASELINE, CANDIDATE):
            return
        branch = int(routing)
        with self._lock:
            self._err[branch].append(1 if float(reward) < 0.5 else 0)
            if branch == CANDIDATE and self.phase == CANARY:
                self._since_eval += 1
                if self._since_eval >= self.eval_every:
                    self._since_eval = 0
                    self._evaluate_locked()

    # -- the decision ----------------------------------------------------
    def _evaluate_locked(self) -> None:
        """Run one rollback evaluation (callers hold ``self._lock``)."""
        if self._baseline_unfolded:
            # fold pending baseline rows into the detector's running
            # statistics (scores discarded — this call is the fold)
            self._detector.score(
                np.asarray(list(self._baseline_unfolded),
                           dtype=np.float64)[:, None])
            self._baseline_unfolded.clear()
        self.evaluations_total += 1
        reason = evaluate_canary(
            list(self._lat[BASELINE]), list(self._lat[CANDIDATE]),
            list(self._err[BASELINE]), list(self._err[CANDIDATE]),
            self._detector,
            min_samples=self.min_samples,
            outlier_fraction=self.outlier_fraction,
            max_error_rate_excess=self.max_error_rate_excess)
        if reason is not None:
            self.phase = ROLLED_BACK
            self.rollback_reason = reason
            self.rollbacks_total += 1
            logger.warning("canary ROLLED BACK: %s", reason)
        elif (self.promote_after
                and self.evaluations_total >= self.promote_after):
            self.phase = PROMOTED
            logger.info("canary PROMOTED after %d clean evaluations",
                        self.evaluations_total)

    # -- surfaces ----------------------------------------------------------
    def rollback(self, reason: str = "manual") -> None:
        """Operator-forced rollback (the manual override every automatic
        rollout system still needs)."""
        with self._lock:
            if self.phase != ROLLED_BACK:
                self.phase = ROLLED_BACK
                self.rollback_reason = reason
                self.rollbacks_total += 1

    def tags(self) -> Dict[str, Any]:
        out = super().tags()
        with self._lock:
            out.update({
                "canary_phase": self.phase,
                "canary_fraction": self.fraction,
                "canary_rollback_reason": self.rollback_reason,
            })
        return out

    def canary_stats(self) -> Dict[str, Any]:
        """Snapshot for ``MetricsRegistry.sync_controlplane`` (scrape
        thread)."""
        with self._lock:
            cand_err = (float(np.mean(self._err[CANDIDATE]))
                        if self._err[CANDIDATE] else 0.0)
            base_err = (float(np.mean(self._err[BASELINE]))
                        if self._err[BASELINE] else 0.0)
            return {
                "canary_phase": self.phase,
                "canary_phase_code": _PHASE_CODES[self.phase],
                "canary_fraction": self.fraction,
                "canary_routed_total": self._routed,
                "canary_evaluations_total": self.evaluations_total,
                "canary_rollbacks_total": self.rollbacks_total,
                "canary_baseline_error_rate": base_err,
                "canary_candidate_error_rate": cand_err,
            }


class ShadowNode(SeldonComponent):
    """Mirror traffic to a shadow candidate; serve only the primary.

    ``predict``/``generate`` always run the primary and return its
    response; every ``mirror_fraction``-th request (the same deterministic
    counter split as the canary) is ALSO sent to the shadow, whose
    response is compared — max-abs-diff for arrays, exact match for token
    lists — and discarded.  Shadow latency is measured on the injectable
    ``clock``; shadow exceptions increment a counter and are swallowed.
    The divergence record is the promotion evidence a canary phase then
    bets real traffic on (docs/control-plane.md "Shadow nodes")."""

    def __init__(
        self,
        primary: Any,
        shadow: Any,
        mirror_fraction: float = 1.0,
        clock: Any = None,
        **kwargs: Any,
    ):
        super().__init__(**kwargs)
        import time

        self.primary = primary
        self.shadow = shadow
        self.mirror_fraction = float(mirror_fraction)
        self.clock = clock if clock is not None else time.perf_counter
        self._lock = threading.Lock()
        self._seen = 0
        self.mirrors_total = 0
        self.shadow_errors_total = 0
        self.divergences_total = 0
        self.max_abs_diff = 0.0
        self.latency_delta_s_sum = 0.0

    def load(self) -> None:
        for c in (self.primary, self.shadow):
            if hasattr(c, "load"):
                c.load()

    def _should_mirror(self) -> bool:
        with self._lock:
            n = self._seen
            self._seen += 1
        return canary_split(n, self.mirror_fraction) == CANDIDATE

    def _record(self, diverged: bool, diff: float, delta_s: float) -> None:
        with self._lock:
            self.mirrors_total += 1
            self.latency_delta_s_sum += delta_s
            if diverged:
                self.divergences_total += 1
            if diff > self.max_abs_diff:
                self.max_abs_diff = diff

    def _record_error(self) -> None:
        with self._lock:
            self.mirrors_total += 1
            self.shadow_errors_total += 1

    @staticmethod
    def _compare(a: Any, b: Any) -> float:
        """Output divergence as a max-abs-diff (arrays) or 0/inf exact
        match (anything else, token lists included)."""
        try:
            aa, bb = np.asarray(a, dtype=np.float64), np.asarray(
                b, dtype=np.float64)
            if aa.shape != bb.shape:
                return float("inf")
            if aa.size == 0:
                return 0.0
            return float(np.max(np.abs(aa - bb)))
        except (TypeError, ValueError):
            return 0.0 if a == b else float("inf")

    def _mirror(self, method: str, *args: Any, **kwargs: Any):
        import inspect

        t0 = self.clock()
        fn = getattr(self.primary, method)
        out = fn(*args, **kwargs)
        t1 = self.clock()
        if inspect.isawaitable(out):
            # async primary: the engine awaits the result downstream and
            # a sync wrapper cannot observe it — delegate without
            # mirroring rather than comparing un-run coroutines
            return out
        if self._should_mirror():
            try:
                s_out = getattr(self.shadow, method)(*args, **kwargs)
                t2 = self.clock()
                if inspect.isawaitable(s_out):
                    s_out.close()
                    raise TypeError(
                        f"async shadow component {type(self.shadow).__name__}"
                        " cannot be mirrored from a sync primary")
                diff = self._compare(out, s_out)
                self._record(diff != 0.0, 0.0 if diff == float("inf")
                             else diff, (t2 - t1) - (t1 - t0))
            except Exception:
                # the shadow exists to fail safely: record, never raise
                logger.exception("shadow %s failed", method)
                self._record_error()
        return out

    def predict(self, X, names, meta=None):
        return self._mirror("predict", X, names, meta)

    def generate(self, *args: Any, **kwargs: Any):
        return self._mirror("generate", *args, **kwargs)

    def tags(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "shadowing": type(self.shadow).__name__,
                "shadow_mirrors": self.mirrors_total,
                "shadow_divergences": self.divergences_total,
            }

    def shadow_stats(self) -> Dict[str, Any]:
        """Snapshot for ``MetricsRegistry.sync_controlplane``."""
        with self._lock:
            return {
                "shadow_mirrors_total": self.mirrors_total,
                "shadow_errors_total": self.shadow_errors_total,
                "shadow_divergences_total": self.divergences_total,
                "shadow_max_abs_diff": self.max_abs_diff,
                "shadow_latency_delta_s_sum": self.latency_delta_s_sum,
            }
