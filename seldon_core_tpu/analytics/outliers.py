"""Outlier-detection TRANSFORMER components.

Capability parity with the reference's `components/outlier-detection/` tree
(`vae/{CoreVAE.py,OutlierVAE.py}`, `mahalanobis/CoreMahalanobis.py`,
`isolation-forest/CoreIsolationForest.py`): each detector sits in the graph as
a TRANSFORMER that passes features through unchanged while tagging outlier
scores/flags into ``meta.tags`` and emitting gauge metrics — so the model node
downstream still receives the original features and dashboards see the scores.

TPU-first: the Mahalanobis update/score and the VAE train/score paths are
jitted JAX (the reference uses numpy resp. Keras); isolation forest wraps
sklearn (CPU, like the reference) behind the same component surface.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from seldon_core_tpu.components.component import SeldonComponent
from seldon_core_tpu.components.metrics import create_gauge

logger = logging.getLogger(__name__)


class _OutlierTransformer(SeldonComponent):
    """Shared surface: score a batch in transform_input, keep features
    unchanged, expose scores via tags()/metrics()."""

    def __init__(self, threshold: float = 0.0, **kwargs: Any):
        super().__init__(**kwargs)
        self.threshold = float(threshold)
        self._last_scores: Optional[np.ndarray] = None
        # RLock: transform_input holds it while calling score(), which locks
        # again in subclasses that update running state (Mahalanobis).
        self._lock = threading.RLock()

    def __getstate__(self):
        state = dict(self.__dict__)
        state.pop("_lock", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.RLock()

    def score(self, X: np.ndarray) -> np.ndarray:  # pragma: no cover - abstract
        raise NotImplementedError

    def transform_input(self, X, names: Sequence[str], meta: Optional[Dict] = None):
        arr = np.atleast_2d(np.asarray(X, dtype=np.float64))
        with self._lock:
            self._last_scores = np.asarray(self.score(arr), dtype=np.float64)
        return X

    def tags(self) -> Dict[str, Any]:
        if self._last_scores is None:
            return {}
        flags = self._last_scores > self.threshold
        return {
            "outlier_score": [float(s) for s in self._last_scores],
            "is_outlier": [int(f) for f in flags],
        }

    def metrics(self) -> List[Dict[str, Any]]:
        if self._last_scores is None:
            return []
        return [
            create_gauge("outlier_score_max", float(np.max(self._last_scores))),
            create_gauge("n_outliers", float(np.sum(self._last_scores > self.threshold))),
        ]

    def row_slice(self, lo: int, hi: int):
        """(tags, metrics) attributed to rows [lo, hi) of the LAST scored
        batch. This is the contract that lets the serving executor stack k
        concurrent requests into ONE score() call and still hand each
        request its own rows' scores — scoring is row-independent given the
        running state, and the state update is batch-wise (matching the
        reference detector, which also scores per arriving batch:
        components/outlier-detection/mahalanobis/CoreMahalanobis.py:42-80).
        For a solo request (lo=0, hi=n) this equals tags()/metrics()."""
        with self._lock:
            if self._last_scores is None or hi > len(self._last_scores):
                return {}, []
            s = np.array(self._last_scores[lo:hi])
        flags = s > self.threshold
        tags = {
            "outlier_score": [float(x) for x in s],
            "is_outlier": [int(f) for f in flags],
        }
        mets = [
            create_gauge("outlier_score_max", float(np.max(s))),
            create_gauge("n_outliers", float(np.sum(flags))),
        ]
        return tags, mets


class MahalanobisOutlierDetector(_OutlierTransformer):
    """Online Mahalanobis distance (`mahalanobis/CoreMahalanobis.py:191`):
    scores each batch against the running mean/covariance *before* folding the
    batch into the statistics, with an effective-sample clip ``n_clip`` so the
    estimator tracks drift. The score+update is one jitted JAX function.
    """

    def __init__(
        self,
        threshold: float = 3.0,
        n_components: int = 0,
        n_clip: int = 1000,
        reg_eps: float = 1e-6,
        **kwargs: Any,
    ):
        super().__init__(threshold=threshold, **kwargs)
        self.n_components = int(n_components)
        self.n_clip = int(n_clip)
        self.reg_eps = float(reg_eps)
        self._state: Optional[Tuple[Any, Any, Any]] = None  # (mean, cov, n)
        self._step = None

    # Serving pads batches up to these row counts so the jitted step sees a
    # handful of static shapes instead of one compile per distinct batch
    # size (the executor's request stacking produces arbitrary row totals;
    # an unseen total used to cost a ~0.4 s XLA compile mid-traffic).
    _ROW_BUCKETS = (1, 16, 256)

    def _build(self, d: int):
        import jax
        import jax.numpy as jnp

        reg_eps = self.reg_eps
        n_clip = float(self.n_clip)

        def step(state, X, n_valid):
            # X is zero-padded to a row bucket; n_valid rows are real. The
            # masked moments make padding exactly a no-op for the running
            # statistics; padded rows' scores are garbage and sliced off by
            # the caller.
            mean, cov, n = state
            mask = (jnp.arange(X.shape[0]) < n_valid).astype(X.dtype)
            Xc = X - mean
            prec = jnp.linalg.inv(cov + reg_eps * jnp.eye(d))
            scores = jnp.sqrt(jnp.maximum(jnp.einsum("bi,ij,bj->b", Xc, prec, Xc), 0.0))

            # fold the batch into the running statistics (clipped n so the
            # estimator keeps adapting)
            b = n_valid.astype(X.dtype)
            bs = jnp.maximum(b, 1.0)
            batch_mean = jnp.sum(X * mask[:, None], axis=0) / bs
            delta = batch_mean - mean
            n_new = n + b
            new_mean = mean + delta * (b / n_new)
            Xb = (X - batch_mean) * mask[:, None]
            batch_cov = (Xb.T @ Xb) / bs
            w_old = n / n_new
            w_b = b / n_new
            new_cov = w_old * cov + w_b * batch_cov + w_old * w_b * jnp.outer(delta, delta)
            n_new = jnp.minimum(n_new, n_clip)
            return scores, (new_mean, new_cov, n_new)

        return jax.jit(step)

    def score(self, X: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp

        X = np.atleast_2d(np.asarray(X, dtype=np.float64))
        if self.n_components and X.shape[1] > self.n_components:
            # cheap spectral projection instead of the reference's sklearn PCA
            X = X[:, : self.n_components]
        rows, d = X.shape
        padded = next((b for b in self._ROW_BUCKETS if b >= rows), None)
        if padded is None:  # beyond the top bucket: round up to its multiple
            top = self._ROW_BUCKETS[-1]
            padded = -(-rows // top) * top
        if padded != rows:
            X = np.concatenate(
                [X, np.zeros((padded - rows, d), X.dtype)], axis=0)
        with self._lock:
            if self._state is None:
                self._state = (
                    jnp.zeros((d,), jnp.float32),
                    jnp.eye(d, dtype=jnp.float32),
                    jnp.asarray(0.0, jnp.float32),
                )
                self._step = self._build(d)
                # compile every row bucket NOW (readiness-time, before
                # traffic): a bucket first seen under load would stall the
                # serving loop behind its XLA compile
                zero_n = jnp.asarray(0.0, jnp.float32)
                for b in self._ROW_BUCKETS:
                    self._step(self._state, jnp.zeros((b, d), jnp.float32), zero_n)
            scores, self._state = self._step(
                self._state, jnp.asarray(X, dtype=jnp.float32),
                jnp.asarray(rows, jnp.float32))
        return np.asarray(scores)[:rows]

    def reset_stats(self) -> None:
        """Zero the running statistics while KEEPING the compiled step:
        the readiness-time prewarm pattern (score a dummy batch to pay
        the jit compile up front, then reset) — the canary router uses it
        so its first real evaluation, which runs under the router lock on
        the serving thread, is a sub-ms compiled dispatch instead of a
        multi-second trace+compile."""
        import jax.numpy as jnp

        with self._lock:
            if self._state is None:
                return
            d = int(self._state[0].shape[0])
            self._state = (
                jnp.zeros((d,), jnp.float32),
                jnp.eye(d, dtype=jnp.float32),
                jnp.asarray(0.0, jnp.float32),
            )

    def score_frozen(self, X: np.ndarray) -> np.ndarray:
        """Score WITHOUT folding the batch into the running statistics:
        the state is saved before and restored after the (score-then-fold)
        step.  The canary comparison needs this (analytics/canary.py):
        candidate windows scored against the baseline distribution must
        not shift that distribution toward themselves — a sustained
        degradation would otherwise normalize itself out of rollback.
        The save/score/restore triple is not atomic against concurrent
        ``score`` calls; callers that mix both serialize externally (the
        canary router holds its own lock)."""
        with self._lock:
            saved = self._state
        scores = self.score(X)
        with self._lock:
            self._state = saved
        return scores

    # jax buffers don't pickle portably; persist as numpy.
    def __getstate__(self):
        state = super().__getstate__()
        state.pop("_step", None)
        if state.get("_state") is not None:
            state["_state"] = tuple(np.asarray(s) for s in state["_state"])
        return state

    def __setstate__(self, state):
        super().__setstate__(state)
        self._step = None
        if self._state is not None:
            import jax.numpy as jnp

            self._state = tuple(jnp.asarray(s) for s in self._state)
            self._step = self._build(int(self._state[0].shape[0]))


class IsolationForestOutlierDetector(_OutlierTransformer):
    """sklearn isolation forest (`isolation-forest/CoreIsolationForest.py:116`):
    fit offline on clean data (or load a joblib artifact from ``model_uri``),
    score = -decision_function so higher means more anomalous."""

    def __init__(
        self,
        threshold: float = 0.0,
        model_uri: str = "",
        n_estimators: int = 100,
        contamination: float = 0.01,
        seed: int = 0,
        **kwargs: Any,
    ):
        super().__init__(threshold=threshold, **kwargs)
        self.model_uri = model_uri
        self.n_estimators = int(n_estimators)
        self.contamination = float(contamination)
        self.seed = int(seed)
        self._clf = None

    def load(self) -> None:
        if self._clf is not None or not self.model_uri:
            return
        import joblib

        from seldon_core_tpu import storage

        path = storage.download(self.model_uri)
        import os

        candidate = os.path.join(path, "model.joblib")
        self._clf = joblib.load(candidate if os.path.exists(candidate) else path)

    def fit(self, X: np.ndarray) -> "IsolationForestOutlierDetector":
        from sklearn.ensemble import IsolationForest

        self._clf = IsolationForest(
            n_estimators=self.n_estimators,
            contamination=self.contamination,
            random_state=self.seed,
        ).fit(np.asarray(X))
        return self

    def score(self, X: np.ndarray) -> np.ndarray:
        if self._clf is None:
            self.load()
        if self._clf is None:
            raise RuntimeError("IsolationForestOutlierDetector needs fit() or model_uri")
        return -self._clf.decision_function(np.asarray(X))


class VAEOutlierDetector(_OutlierTransformer):
    """Variational autoencoder reconstruction-error detector
    (`vae/{CoreVAE.py:181,OutlierVAE.py:118}`), rebuilt as a Flax MLP VAE with
    a jitted optax train loop; score = per-sample reconstruction MSE (the
    reference thresholds Keras reconstruction loss the same way)."""

    def __init__(
        self,
        threshold: float = 0.1,
        latent_dim: int = 2,
        hidden_dim: int = 64,
        seed: int = 0,
        **kwargs: Any,
    ):
        super().__init__(threshold=threshold, **kwargs)
        self.latent_dim = int(latent_dim)
        self.hidden_dim = int(hidden_dim)
        self.seed = int(seed)
        self._params = None
        self._d: Optional[int] = None
        self._score_fn = None

    def _module(self, d: int):
        import flax.linen as nn
        import jax.numpy as jnp

        latent, hidden = self.latent_dim, self.hidden_dim

        class VAE(nn.Module):
            @nn.compact
            def __call__(self, x, rng):
                import jax

                h = nn.relu(nn.Dense(hidden)(x))
                mu = nn.Dense(latent)(h)
                logvar = nn.Dense(latent)(h)
                eps = jax.random.normal(rng, mu.shape)
                z = mu + jnp.exp(0.5 * logvar) * eps
                h2 = nn.relu(nn.Dense(hidden)(z))
                recon = nn.Dense(d)(h2)
                return recon, mu, logvar

        return VAE()

    def fit(self, X: np.ndarray, epochs: int = 200, lr: float = 1e-3, kl_weight: float = 1e-3):
        import jax
        import jax.numpy as jnp
        import optax

        X = np.atleast_2d(np.asarray(X, dtype=np.float32))
        self._d = X.shape[1]
        module = self._module(self._d)
        key = jax.random.PRNGKey(self.seed)
        params = module.init(key, jnp.asarray(X[:1]), key)

        tx = optax.adam(lr)
        opt_state = tx.init(params)

        def loss_fn(params, x, rng):
            recon, mu, logvar = module.apply(params, x, rng)
            mse = jnp.mean(jnp.sum((recon - x) ** 2, axis=-1))
            kl = -0.5 * jnp.mean(jnp.sum(1 + logvar - mu**2 - jnp.exp(logvar), axis=-1))
            return mse + kl_weight * kl

        @jax.jit
        def train_step(params, opt_state, x, rng):
            loss, grads = jax.value_and_grad(loss_fn)(params, x, rng)
            updates, opt_state = tx.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        xs = jnp.asarray(X)
        for i in range(epochs):
            key, sub = jax.random.split(key)
            params, opt_state, loss = train_step(params, opt_state, xs, sub)
        self._params = params
        self._build_score(module)
        logger.info("VAE fit done: final loss %.5f", float(loss))
        return self

    def _build_score(self, module=None):
        import jax
        import jax.numpy as jnp

        module = module or self._module(self._d)

        @jax.jit
        def score_fn(params, x):
            # deterministic pass: eps drawn with a fixed key, mean path
            recon, mu, logvar = module.apply(params, x, jax.random.PRNGKey(0))
            return jnp.mean((recon - x) ** 2, axis=-1)

        self._score_fn = score_fn

    def score(self, X: np.ndarray) -> np.ndarray:
        if self._params is None:
            raise RuntimeError("VAEOutlierDetector needs fit() before scoring")
        if self._score_fn is None:
            self._build_score()
        import jax.numpy as jnp

        X = np.atleast_2d(np.asarray(X, dtype=np.float32))
        return np.asarray(self._score_fn(self._params, jnp.asarray(X)))

    def __getstate__(self):
        import jax

        state = super().__getstate__()
        state.pop("_score_fn", None)
        if state.get("_params") is not None:
            state["_params"] = jax.tree.map(np.asarray, state["_params"])
        return state

    def __setstate__(self, state):
        super().__setstate__(state)
        self._score_fn = None


class Seq2SeqOutlierDetector(_OutlierTransformer):
    """Sequence reconstruction detector — the 4th detector family
    (`seq2seq-lstm/CoreSeq2SeqLSTM.py:214`): an encoder-decoder over time
    windows whose reconstruction MSE flags anomalous stretches of a series.

    TPU-first: the reference's Keras LSTM pair becomes a Flax GRU
    encoder-decoder trained with a jitted optax loop — recurrence runs as
    ``lax.scan`` under jit (static shapes, no per-step Python), and scoring
    is one compiled program per window-batch shape.

    Input contract: a 3-D batch [B, T, F] scores per sequence; a 2-D batch
    [N, F] (the graph payload case) is framed into non-overlapping
    ``timesteps`` windows (tail padded by repetition) and each row inherits
    its window's score, so tags()/metrics() keep their per-row shape.
    """

    # 2-D scoring frames rows into timesteps windows, so naive row-stacking
    # of concurrent requests would slide window boundaries across request
    # edges (request B's rows scored inside request A's window). The
    # stack_segments protocol (the windowed analogue of row_slice) fixes
    # that: the executor announces each stacked request's row count, rows
    # are framed into windows PER SEGMENT, and the window batch — padded to
    # a compile bucket — scores in one jitted call. row_slice (inherited)
    # then hands each request its own rows' scores, which are identical to
    # its solo scores because no window ever straddles a boundary
    # (tests/test_outliers.py::test_seq2seq_stacked_matches_solo).

    # window-count compile buckets; beyond the top, round up to its multiple
    _W_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)

    def __init__(
        self,
        threshold: float = 0.1,
        timesteps: int = 8,
        hidden_dim: int = 32,
        seed: int = 0,
        model_uri: str = "",
        **kwargs: Any,
    ):
        super().__init__(threshold=threshold, **kwargs)
        self.timesteps = int(timesteps)
        self.hidden_dim = int(hidden_dim)
        self.seed = int(seed)
        self.model_uri = model_uri
        self._params = None
        self._d: Optional[int] = None
        self._score_fn = None
        self._pending_segments: Optional[List[int]] = None

    def load(self) -> None:
        """Adopt a FITTED detector pickled by ``save()`` from model_uri —
        the serving path for a detector trained offline (same contract as
        IsolationForest's joblib artifact; graphs declare
        SEQ2SEQ_OD with a model_uri parameter)."""
        if self._params is not None or not self.model_uri:
            return
        import os
        import pickle

        from seldon_core_tpu import storage

        path = storage.download(self.model_uri)
        candidate = os.path.join(path, "detector.pkl")
        with open(candidate if os.path.exists(candidate) else path, "rb") as f:
            fitted = pickle.load(f)
        if not isinstance(fitted, Seq2SeqOutlierDetector) or fitted._params is None:
            raise RuntimeError(
                f"{self.model_uri} does not contain a fitted "
                "Seq2SeqOutlierDetector (save() one after fit())")
        for attr in ("threshold", "timesteps", "hidden_dim", "seed",
                     "_params", "_d"):
            setattr(self, attr, getattr(fitted, attr))
        self._score_fn = None  # rebuilt lazily for the adopted dims

    def save(self, out_dir: str) -> str:
        """Pickle this fitted detector as <out_dir>/detector.pkl (the
        artifact ``load()`` consumes)."""
        import os
        import pickle

        if self._params is None:
            raise RuntimeError("fit() before save()")
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, "detector.pkl")
        with open(path, "wb") as f:
            pickle.dump(self, f)
        return path

    def stack_segments(self, counts: Sequence[int]) -> None:
        """Executor protocol: the NEXT 2-D score() call's rows are the
        concatenation of ``len(counts)`` requests with these row counts.
        Consumed once; without it a call is one segment (solo semantics)."""
        self._pending_segments = [int(c) for c in counts]

    def _module(self, d: int):
        import flax.linen as nn
        import jax.numpy as jnp

        hidden, T = self.hidden_dim, self.timesteps

        class Seq2SeqAE(nn.Module):
            @nn.compact
            def __call__(self, x):  # [B, T, F]
                enc_out = nn.RNN(nn.GRUCell(hidden))(x)
                code = enc_out[:, -1]  # [B, H] — the sequence encoding
                dec_in = jnp.repeat(code[:, None, :], T, axis=1)
                dec_out = nn.RNN(nn.GRUCell(hidden))(dec_in)
                # reconstruct the REVERSED sequence (classic seq2seq-AE
                # target: last-in, first-out eases the decoder's job)
                return nn.Dense(d)(dec_out)[:, ::-1]

        return Seq2SeqAE()

    # ------------------------------------------------------------------
    def _frame(self, X: np.ndarray) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """[N, F] -> ([W, T, F], row->window index map); 3-D passes through."""
        X = np.asarray(X, dtype=np.float32)
        if X.ndim == 3:
            if X.shape[1] != self.timesteps:
                raise ValueError(
                    f"3-D input must have sequence length {self.timesteps} "
                    f"(the decoder's unroll length), got {X.shape[1]}"
                )
            return X, None
        X = np.atleast_2d(X)
        n, d = X.shape
        T = self.timesteps
        pad = (-n) % T
        if pad:
            X = np.concatenate([X, np.repeat(X[-1:], pad, axis=0)], axis=0)
        windows = X.reshape(-1, T, d)
        row_to_window = np.repeat(np.arange(len(windows)), T)[:n]
        return windows, row_to_window

    def fit(self, X: np.ndarray, epochs: int = 200, lr: float = 1e-2):
        import jax
        import jax.numpy as jnp
        import optax

        windows, _ = self._frame(X)
        self._d = windows.shape[-1]
        module = self._module(self._d)
        key = jax.random.PRNGKey(self.seed)
        params = module.init(key, jnp.asarray(windows[:1]))

        tx = optax.adam(lr)
        opt_state = tx.init(params)

        def loss_fn(params, x):
            recon = module.apply(params, x)
            return jnp.mean((recon - x) ** 2)

        @jax.jit
        def train_step(params, opt_state, x):
            loss, grads = jax.value_and_grad(loss_fn)(params, x)
            updates, opt_state = tx.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        xs = jnp.asarray(windows)
        for _ in range(epochs):
            params, opt_state, loss = train_step(params, opt_state, xs)
        self._params = params
        self._build_score(module)
        logger.info("Seq2Seq fit done: final loss %.6f", float(loss))
        return self

    def _build_score(self, module=None):
        import jax
        import jax.numpy as jnp

        module = module or self._module(self._d)

        @jax.jit
        def score_fn(params, x):  # [W, T, F] -> [W] per-window mse
            recon = module.apply(params, x)
            return jnp.mean((recon - x) ** 2, axis=(1, 2))

        self._score_fn = score_fn

    def _w_bucket(self, w: int) -> int:
        from seldon_core_tpu.utils import bucket

        return bucket(w, self._W_BUCKETS)

    def score(self, X: np.ndarray) -> np.ndarray:
        if self._params is None:
            raise RuntimeError("Seq2SeqOutlierDetector needs fit() before scoring")
        if self._score_fn is None:
            self._build_score()
        import jax.numpy as jnp

        segs, self._pending_segments = self._pending_segments, None
        X = np.asarray(X, dtype=np.float32)
        if X.ndim == 3 or not segs or sum(segs) != np.atleast_2d(X).shape[0]:
            # solo request (or per-sequence 3-D input, where rows are
            # already independent windows): one segment
            windows, row_map = self._frame(X)
        else:
            # stacked 2-D call: frame each request's rows separately so no
            # window straddles a request boundary, then score every window
            # in one batch
            X = np.atleast_2d(X)
            parts, maps, off, woff = [], [], 0, 0
            for c in segs:
                w, m = self._frame(X[off:off + c])
                parts.append(w)
                maps.append(m + woff)
                off += c
                woff += len(w)
            windows = np.concatenate(parts, axis=0)
            row_map = np.concatenate(maps)
        w = len(windows)
        padded = self._w_bucket(w)
        if padded != w:  # repeat-pad to the compile bucket; scores sliced off
            windows = np.concatenate(
                [windows, np.repeat(windows[-1:], padded - w, axis=0)], axis=0)
        per_window = np.asarray(
            self._score_fn(self._params, jnp.asarray(windows)))[:w]
        if row_map is None:
            return per_window
        return per_window[row_map]

    def __getstate__(self):
        import jax

        state = super().__getstate__()
        state.pop("_score_fn", None)
        if state.get("_params") is not None:
            state["_params"] = jax.tree.map(np.asarray, state["_params"])
        return state

    def __setstate__(self, state):
        super().__setstate__(state)
        self._score_fn = None
