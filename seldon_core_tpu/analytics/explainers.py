"""Explainer components.

The reference reserves a per-predictor ``explainer`` slot in the CRD
(`proto/seldon_deployment.proto:45-51,63`) that deploys a sidecar service
answering "why did the model predict this" (alibi-style, CPU). The
TPU-native counterpart exploits what the reference couldn't: the served
model is a differentiable JAX function, so attribution is one compiled
gradient — no surrogate model, no sampling loop.

``SaliencyExplainer`` loads the SAME checkpoint as the model it explains
and serves attributions through the standard component contract: predict(X)
returns gradient x input per feature (integrated gradients when steps > 1),
jitted per batch-shape bucket.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional, Sequence

import numpy as np

from seldon_core_tpu.components.component import SeldonComponent
from seldon_core_tpu.contracts.payload import SeldonError

logger = logging.getLogger(__name__)


class SaliencyExplainer(SeldonComponent):
    """Gradient-based attribution for a JAXServer checkpoint.

    Parameters: model_uri (the checkpoint to explain), target ("max" = the
    argmax logit, or an int class index), steps (1 = plain grad x input;
    >1 = integrated gradients along the zero baseline path).
    """

    def __init__(
        self,
        model_uri: str = "",
        target: Any = "max",
        steps: int = 1,
        batch_buckets: Any = None,
        **kwargs: Any,
    ):
        super().__init__(**kwargs)
        self.model_uri = model_uri
        self.target = target
        self.steps = int(steps)
        self.batch_buckets = tuple(batch_buckets) if batch_buckets else None
        self.ready = False
        self._grad_fn = None

    def load(self) -> None:
        if self.ready:
            return
        import jax
        import jax.numpy as jnp

        from seldon_core_tpu.servers.jaxserver import JAXServer

        server = JAXServer(model_uri=self.model_uri)
        apply, params = server.jax_fn()  # loads; public composition surface
        if self.batch_buckets is None:
            self.batch_buckets = server.batch_buckets
        target = self.target
        steps = self.steps

        def scalar_out(x):
            out = apply(params, x)
            if isinstance(target, int) or (isinstance(target, str) and target.isdigit()):
                picked = out[..., int(target)]
            else:  # "max": the predicted class's logit/probability
                picked = jnp.max(out, axis=-1)
            return picked.sum()

        grad_fn = jax.grad(scalar_out)

        @jax.jit
        def attribute(x):
            if steps <= 1:
                return grad_fn(x) * x
            # integrated gradients: average grads along the 0 -> x path
            alphas = jnp.linspace(1.0 / steps, 1.0, steps)

            def body(acc, a):
                return acc + grad_fn(x * a), None

            total, _ = jax.lax.scan(body, jnp.zeros_like(x), alphas)
            return (total / steps) * x

        self._grad_fn = attribute
        self._input_dtype = server.input_dtype
        self.ready = True
        logger.info("SaliencyExplainer ready over %s (steps=%d)", self.model_uri, steps)

    def predict(self, X, names: Sequence[str], meta: Optional[Dict] = None) -> np.ndarray:
        if not self.ready:
            self.load()
        # gradients are taken wrt the model INPUT: the checkpoint must take
        # continuous features (an int-input model, e.g. token ids, has no
        # meaningful input gradient); numeric requests cast to that dtype
        if not np.issubdtype(self._input_dtype, np.floating):
            raise SeldonError(
                f"saliency needs a float-input model, checkpoint declares "
                f"{self._input_dtype}", status_code=400,
            )
        raw = np.asarray(X)
        if not np.issubdtype(raw.dtype, np.number):
            raise SeldonError("saliency explanations need numeric inputs", status_code=400)
        arr = raw.astype(self._input_dtype, copy=False)
        # same bucketing as the server: one compiled gradient program per
        # bucket, not per request batch size
        from seldon_core_tpu.codec.staging import pad_batch

        padded, true_n = pad_batch(arr, self.batch_buckets)
        attributions = self._grad_fn(padded)
        return np.asarray(attributions)[:true_n]

    def tags(self) -> Dict[str, Any]:
        return {"explainer": "saliency", "steps": self.steps, "target": str(self.target)}
