"""Deterministic test harnesses (fault injection, clocks)."""

from seldon_core_tpu.testing.faults import (  # noqa: F401
    FaultClock,
    FaultSchedule,
    FaultSpec,
    FaultyComponent,
    inject_faults,
)
