"""Deterministic fault injection for resilience tests.

Everything here is seeded or explicitly scheduled — no wall-clock randomness
and no real sleeps. Latency is injected by advancing a :class:`FaultClock`
(the same clock object handed to Deadline/CircuitBreaker), so a test can
"burn" 200ms of budget in zero wall time and still observe exact
deadline-exceeded and breaker open/half-open/recovery transitions.

Typical wiring::

    clock = FaultClock()
    schedule = FaultSchedule.flaps("EEEEEO")        # 5 errors then ok
    comp = FaultyComponent(schedule, clock=clock)
    engine = GraphEngine(spec, components={"m": comp},
                         resilience=ResilienceConfig(breaker_failures=5,
                                                     breaker_reset_s=1.0,
                                                     clock=clock))
    # ... drive predict(), advance clock, assert breaker transitions

Schedules are per-call: call i consults ``schedule[i]`` (the last entry
repeats once the schedule is exhausted, so a finite schedule describes an
infinite behavior).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from seldon_core_tpu.components.component import SeldonComponent
from seldon_core_tpu.contracts.payload import SeldonError


class FaultClock:
    """A manually-advanced monotonic clock. Pass the instance anywhere a
    ``clock`` callable is expected (Deadline, CircuitBreaker,
    ResilienceConfig) — calling it returns the current fake time."""

    def __init__(self, start: float = 1000.0):
        self.t = float(start)

    def __call__(self) -> float:
        return self.t

    def now(self) -> float:
        return self.t

    def advance(self, seconds: float) -> float:
        if seconds < 0:
            raise ValueError("clocks only move forward")
        self.t += seconds
        return self.t


@dataclass
class FaultSpec:
    """Behavior of one call: optional injected latency (FaultClock seconds),
    then either success or a raised error."""

    latency_s: float = 0.0
    error: Optional[BaseException] = None

    @classmethod
    def ok(cls, latency_s: float = 0.0) -> "FaultSpec":
        return cls(latency_s=latency_s)

    @classmethod
    def fail(cls, message: str = "injected fault", status_code: int = 503,
             latency_s: float = 0.0) -> "FaultSpec":
        return cls(
            latency_s=latency_s,
            error=SeldonError(message, status_code=status_code, reason="INJECTED_FAULT"),
        )


class FaultSchedule:
    """A deterministic per-call schedule of FaultSpecs. Indexing past the end
    repeats the final entry."""

    def __init__(self, specs: Sequence[FaultSpec]):
        if not specs:
            raise ValueError("schedule needs at least one entry")
        self.specs: List[FaultSpec] = list(specs)

    def __getitem__(self, i: int) -> FaultSpec:
        return self.specs[min(i, len(self.specs) - 1)]

    def __len__(self) -> int:
        return len(self.specs)

    # -- constructors ---------------------------------------------------
    @classmethod
    def always_ok(cls, latency_s: float = 0.0) -> "FaultSchedule":
        return cls([FaultSpec.ok(latency_s)])

    @classmethod
    def always_fail(cls, status_code: int = 503) -> "FaultSchedule":
        return cls([FaultSpec.fail(status_code=status_code)])

    @classmethod
    def flaps(cls, pattern: str, latency_s: float = 0.0,
              status_code: int = 503) -> "FaultSchedule":
        """``pattern``: one char per call — 'E' error, 'O' ok. E.g.
        ``"EEEEEO"`` fails five calls then succeeds forever (final entry
        repeats)."""
        specs = []
        for ch in pattern:
            if ch in ("E", "e", "F", "f"):
                specs.append(FaultSpec.fail(status_code=status_code, latency_s=latency_s))
            elif ch in ("O", "o", ".", "S", "s"):
                specs.append(FaultSpec.ok(latency_s))
            else:
                raise ValueError(f"unknown flap char {ch!r} (use E/O)")
        return cls(specs)

    @classmethod
    def seeded(
        cls,
        seed: int,
        n: int,
        error_rate: float = 0.0,
        latency_s: float = 0.0,
        latency_jitter_s: float = 0.0,
        status_code: int = 503,
    ) -> "FaultSchedule":
        """n entries drawn from random.Random(seed): same seed, same
        schedule, forever — CI-stable chaos."""
        rng = random.Random(seed)
        specs = []
        for _ in range(n):
            lat = latency_s + (rng.random() * latency_jitter_s if latency_jitter_s else 0.0)
            if rng.random() < error_rate:
                specs.append(FaultSpec.fail(status_code=status_code, latency_s=lat))
            else:
                specs.append(FaultSpec.ok(lat))
        return cls(specs)


class FaultyComponent(SeldonComponent):
    """A graph node with scripted behavior.

    Wraps an ``inner`` component (default: echo) and, per call, advances the
    attached FaultClock by the scheduled latency then raises the scheduled
    error or delegates. ``is_async=True`` (the default) makes the engine
    treat it like a remote/async node — the class the resilience layer wraps
    with breakers. ``calls`` records every invocation so tests can prove a
    short-circuited node never executed.
    """

    def __init__(
        self,
        schedule: Optional[FaultSchedule] = None,
        clock: Optional[FaultClock] = None,
        inner: Optional[SeldonComponent] = None,
        is_async: bool = True,
        name: str = "faulty",
    ):
        super().__init__()
        self.schedule = schedule or FaultSchedule.always_ok()
        self.clock = clock
        self.inner = inner
        self.is_async = is_async
        self.name = name
        self.calls = 0
        self.on_call: Optional[Callable[[int, FaultSpec], None]] = None

    # -- fault application ---------------------------------------------
    def _apply(self) -> None:
        spec = self.schedule[self.calls]
        self.calls += 1
        if self.on_call is not None:
            self.on_call(self.calls - 1, spec)
        if spec.latency_s and self.clock is not None:
            self.clock.advance(spec.latency_s)
        if spec.error is not None:
            raise spec.error

    def _delegate(self, method: str, X, names, meta=None):
        self._apply()
        if self.inner is not None:
            fn = getattr(self.inner, method, None)
            if fn is not None:
                return fn(X, names, meta=meta)
        return X

    # -- component surface (async: the engine's breaker-wrapped class) --
    async def predict(self, X, names, meta=None):
        return self._delegate("predict", X, names, meta)

    async def transform_input(self, X, names, meta=None):
        return self._delegate("transform_input", X, names, meta)

    async def transform_output(self, X, names, meta=None):
        return self._delegate("transform_output", X, names, meta)

    async def route(self, X, names):
        self._apply()
        if self.inner is not None and hasattr(self.inner, "route"):
            return self.inner.route(X, names)
        return 0

    async def aggregate(self, Xs, names):
        self._apply()
        if self.inner is not None and hasattr(self.inner, "aggregate"):
            return self.inner.aggregate(Xs, names)
        return np.mean([np.asarray(x) for x in Xs], axis=0)


def inject_faults(
    component: SeldonComponent,
    schedule: FaultSchedule,
    clock: Optional[FaultClock] = None,
) -> FaultyComponent:
    """Wrap an existing component with a fault schedule (its methods run only
    when the scheduled call succeeds)."""
    return FaultyComponent(schedule=schedule, clock=clock, inner=component)
