"""Deterministic fault injection for resilience tests.

Everything here is seeded or explicitly scheduled — no wall-clock randomness
and no real sleeps. Latency is injected by advancing a :class:`FaultClock`
(the same clock object handed to Deadline/CircuitBreaker), so a test can
"burn" 200ms of budget in zero wall time and still observe exact
deadline-exceeded and breaker open/half-open/recovery transitions.

Typical wiring::

    clock = FaultClock()
    schedule = FaultSchedule.flaps("EEEEEO")        # 5 errors then ok
    comp = FaultyComponent(schedule, clock=clock)
    engine = GraphEngine(spec, components={"m": comp},
                         resilience=ResilienceConfig(breaker_failures=5,
                                                     breaker_reset_s=1.0,
                                                     clock=clock))
    # ... drive predict(), advance clock, assert breaker transitions

Schedules are per-call: call i consults ``schedule[i]`` (the last entry
repeats once the schedule is exhausted, so a finite schedule describes an
infinite behavior).
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from seldon_core_tpu.components.component import SeldonComponent
from seldon_core_tpu.contracts.payload import SeldonError


class FaultClock:
    """A manually-advanced monotonic clock. Pass the instance anywhere a
    ``clock`` callable is expected (Deadline, CircuitBreaker,
    ResilienceConfig) — calling it returns the current fake time."""

    def __init__(self, start: float = 1000.0):
        self.t = float(start)

    def __call__(self) -> float:
        return self.t

    def now(self) -> float:
        return self.t

    def advance(self, seconds: float) -> float:
        if seconds < 0:
            raise ValueError("clocks only move forward")
        self.t += seconds
        return self.t


@dataclass
class FaultSpec:
    """Behavior of one call: optional injected latency (FaultClock seconds),
    then either success or a raised error."""

    latency_s: float = 0.0
    error: Optional[BaseException] = None

    @classmethod
    def ok(cls, latency_s: float = 0.0) -> "FaultSpec":
        return cls(latency_s=latency_s)

    @classmethod
    def fail(cls, message: str = "injected fault", status_code: int = 503,
             latency_s: float = 0.0) -> "FaultSpec":
        return cls(
            latency_s=latency_s,
            error=SeldonError(message, status_code=status_code, reason="INJECTED_FAULT"),
        )


class FaultSchedule:
    """A deterministic per-call schedule of FaultSpecs. Indexing past the end
    repeats the final entry."""

    def __init__(self, specs: Sequence[FaultSpec]):
        if not specs:
            raise ValueError("schedule needs at least one entry")
        self.specs: List[FaultSpec] = list(specs)

    def __getitem__(self, i: int) -> FaultSpec:
        return self.specs[min(i, len(self.specs) - 1)]

    def __len__(self) -> int:
        return len(self.specs)

    # -- constructors ---------------------------------------------------
    @classmethod
    def always_ok(cls, latency_s: float = 0.0) -> "FaultSchedule":
        return cls([FaultSpec.ok(latency_s)])

    @classmethod
    def always_fail(cls, status_code: int = 503) -> "FaultSchedule":
        return cls([FaultSpec.fail(status_code=status_code)])

    @classmethod
    def flaps(cls, pattern: str, latency_s: float = 0.0,
              status_code: int = 503) -> "FaultSchedule":
        """``pattern``: one char per call — 'E' error, 'O' ok. E.g.
        ``"EEEEEO"`` fails five calls then succeeds forever (final entry
        repeats)."""
        specs = []
        for ch in pattern:
            if ch in ("E", "e", "F", "f"):
                specs.append(FaultSpec.fail(status_code=status_code, latency_s=latency_s))
            elif ch in ("O", "o", ".", "S", "s"):
                specs.append(FaultSpec.ok(latency_s))
            else:
                raise ValueError(f"unknown flap char {ch!r} (use E/O)")
        return cls(specs)

    @classmethod
    def seeded(
        cls,
        seed: int,
        n: int,
        error_rate: float = 0.0,
        latency_s: float = 0.0,
        latency_jitter_s: float = 0.0,
        status_code: int = 503,
    ) -> "FaultSchedule":
        """n entries drawn from random.Random(seed): same seed, same
        schedule, forever — CI-stable chaos."""
        rng = random.Random(seed)
        specs = []
        for _ in range(n):
            lat = latency_s + (rng.random() * latency_jitter_s if latency_jitter_s else 0.0)
            if rng.random() < error_rate:
                specs.append(FaultSpec.fail(status_code=status_code, latency_s=lat))
            else:
                specs.append(FaultSpec.ok(lat))
        return cls(specs)


class FaultyComponent(SeldonComponent):
    """A graph node with scripted behavior.

    Wraps an ``inner`` component (default: echo) and, per call, advances the
    attached FaultClock by the scheduled latency then raises the scheduled
    error or delegates. ``is_async=True`` (the default) makes the engine
    treat it like a remote/async node — the class the resilience layer wraps
    with breakers. ``calls`` records every invocation so tests can prove a
    short-circuited node never executed.
    """

    def __init__(
        self,
        schedule: Optional[FaultSchedule] = None,
        clock: Optional[FaultClock] = None,
        inner: Optional[SeldonComponent] = None,
        is_async: bool = True,
        name: str = "faulty",
    ):
        super().__init__()
        self.schedule = schedule or FaultSchedule.always_ok()
        self.clock = clock
        self.inner = inner
        self.is_async = is_async
        self.name = name
        self.calls = 0
        self.on_call: Optional[Callable[[int, FaultSpec], None]] = None

    # -- fault application ---------------------------------------------
    def _apply(self) -> None:
        spec = self.schedule[self.calls]
        self.calls += 1
        if self.on_call is not None:
            self.on_call(self.calls - 1, spec)
        if spec.latency_s and self.clock is not None:
            self.clock.advance(spec.latency_s)
        if spec.error is not None:
            raise spec.error

    def _delegate(self, method: str, X, names, meta=None):
        self._apply()
        if self.inner is not None:
            fn = getattr(self.inner, method, None)
            if fn is not None:
                return fn(X, names, meta=meta)
        return X

    # -- component surface (async: the engine's breaker-wrapped class) --
    async def predict(self, X, names, meta=None):
        return self._delegate("predict", X, names, meta)

    async def transform_input(self, X, names, meta=None):
        return self._delegate("transform_input", X, names, meta)

    async def transform_output(self, X, names, meta=None):
        return self._delegate("transform_output", X, names, meta)

    async def route(self, X, names):
        self._apply()
        if self.inner is not None and hasattr(self.inner, "route"):
            return self.inner.route(X, names)
        return 0

    async def aggregate(self, Xs, names):
        self._apply()
        if self.inner is not None and hasattr(self.inner, "aggregate"):
            return self.inner.aggregate(Xs, names)
        return np.mean([np.asarray(x) for x in Xs], axis=0)


def inject_faults(
    component: SeldonComponent,
    schedule: FaultSchedule,
    clock: Optional[FaultClock] = None,
) -> FaultyComponent:
    """Wrap an existing component with a fault schedule (its methods run only
    when the scheduled call succeeds)."""
    return FaultyComponent(schedule=schedule, clock=clock, inner=component)


# ---------------------------------------------------------------------------
# Fleet chaos (ISSUE 16): deterministic batcher-level crash injection.
#
# ContinuousBatcher calls its ``_chaos`` hook at the top of every loop turn
# with itself as the argument; a raising hook is indistinguishable from a
# device fault mid-step — the crash handler fails every in-flight slot and
# the loop dies, exactly the unplanned death the fleet's health model must
# catch. No sleeps anywhere: triggers are explicit (a threading.Event the
# test sets, or any predicate over batcher state), so the kill lands
# mid-decode by construction rather than by timing luck.


class BatcherKiller:
    """A one-shot batcher-loop assassin, installable as ``batcher._chaos``
    on any number of batchers at once.

    The kill fires on the first loop turn where ``trigger`` is truthy (an
    ``threading.Event`` works directly — so does any zero-arg callable or
    a predicate taking the batcher). With ``busiest=True`` and several
    installed batchers, only the batcher holding the most active slots at
    trigger time dies — "kill the busiest replica mid-decode" without
    guessing which replica the router chose. One shot: after the kill the
    hook disarms everywhere, so the fleet's half-open re-probe (which
    restarts the very same loop) finds a healthy batcher.
    """

    def __init__(self, trigger: Optional[Any] = None, busiest: bool = False,
                 message: str = "chaos: batcher loop killed"):
        self.trigger = trigger
        self.busiest = busiest
        self.message = message
        self._armed = True
        self._lock = threading.Lock()
        self._installed: List[Any] = []
        self.kills = 0
        self.killed: Optional[Any] = None  # the batcher that died

    def install(self, *batchers: Any) -> "BatcherKiller":
        """Attach to each batcher's ``_chaos`` hook; returns self."""
        for b in batchers:
            b._chaos = self
            self._installed.append(b)
        return self

    def _triggered(self, batcher: Any) -> bool:
        t = self.trigger
        if t is None:
            return True
        if hasattr(t, "is_set"):
            return bool(t.is_set())
        try:
            return bool(t(batcher))
        except TypeError:
            return bool(t())

    @staticmethod
    def _active_slots(batcher: Any) -> int:
        return sum(1 for s in batcher._slots if s.active)

    def __call__(self, batcher: Any) -> None:
        # each batcher loop runs on its own event-loop thread: the disarm
        # is a check-then-set race between victims, so it sits under a lock
        with self._lock:
            if not self._armed or not self._triggered(batcher):
                return
            if self.busiest:
                mine = self._active_slots(batcher)
                peak = max((self._active_slots(b) for b in self._installed),
                           default=0)
                if mine == 0 or mine < peak:
                    return  # a busier sibling will take the bullet
            self._armed = False
            self.kills += 1
            self.killed = batcher
        raise SeldonError(self.message, status_code=503,
                          reason="INJECTED_FAULT")


class HandoffPoisoner:
    """Corrupts the staged KV of finished remote prefills so the decode
    side's import raises — the "poisoned handoff" fault class.

    Wraps every PrefillWorker's ``_prefill_one``: the prefill itself runs
    and publishes normally, but the handoff arrives READY with ``staged``
    replaced by an unimportable payload (a bare string has no pages to
    slice dense-insert or tree-import, so both layouts raise inside
    ``_consume_handoffs``). Poisons the first ``first_n`` handoffs, then
    passes everything through untouched — one bad handoff amid good ones,
    the shape the batcher's containment must survive.

    Network transport (``handoff_transport="network"``): the poison moves
    to the WIRE — ``_frame_handoff``'s framed bytes are truncated inside
    the tensor region, so the decode host's HandoffReceiver hits the
    frame codec's bounds check (metadata — and so the job_id — stays
    parseable, by the frame's meta-before-payload layout) and resolves
    the job with an error handoff. Same containment contract, proven one
    layer deeper."""

    def __init__(self, batcher: Any, first_n: int = 1,
                 poison: Any = "poisoned-kv-payload"):
        self.first_n = int(first_n)
        self.poison = poison
        self.poisoned = 0
        self._lock = threading.Lock()
        if getattr(batcher, "_remote", None) is None:
            raise ValueError("HandoffPoisoner needs a disaggregated batcher")
        for worker in batcher._remote.workers:
            if getattr(worker, "transport", "device") == "network":
                real_frame = worker._frame_handoff

                def poisoned_frame(h, _real=real_frame):
                    payload = _real(h)
                    with self._lock:
                        if self.poisoned < self.first_n:
                            self.poisoned += 1
                            payload = payload[:-16]
                    return payload

                worker._frame_handoff = poisoned_frame
                continue
            real = worker._prefill_one

            def poisoned_prefill(req, _real=real):
                h = _real(req)
                with self._lock:
                    if self.poisoned < self.first_n:
                        self.poisoned += 1
                        h.staged = self.poison
                return h

            worker._prefill_one = poisoned_prefill


class LeakSweep:
    """Error-path leak harness (ISSUE 19): one-shot fault injection at
    every registered acquire/commit boundary of a live batcher, plus a
    zero-residue probe over every refcounted resource the runtime owns.

    The static half of PR 19 (``tools/leaklint``) proves each acquire
    site pairs with a release on every CFG path; this is the dynamic
    half — it makes those paths actually EXECUTE. For each boundary the
    harness arms a deterministic one-shot fault, the test drives one
    request through it (which fails with a contained error — the server
    must keep serving), and ``assert_clean`` then checks that every
    counter an unwind path is responsible for is back to zero: pages
    held by slots, elevated trie pins, adapter pins, staged remote
    jobs, undelivered handoffs, resume-journal entries.

    Boundaries map 1:1 onto the leaklint effect registry
    (``tools/leaklint/effects.py``):

    ========================  =============================================
    boundary                  injected fault (one-shot)
    ========================  =============================================
    ``adapter-pin``           ``AdapterRegistry.resolve_and_pin`` raises
                              KeyError at submit — the 400 path must drop
                              nothing (no pin was taken under the raise).
    ``page-alloc``            ``_alloc_pages`` returns None while armed —
                              admission exhaustion; the unwind must drop
                              the ``match_and_pin`` prefix pins (the PR 7 /
                              PR 15 leak class).
    ``radix-cow``             only the FIRST ``_alloc_pages`` call fails —
                              the cow-drop retry path runs and the request
                              SUCCEEDS; the dropped cow-source pin must be
                              freed exactly once (the PR 12 leak class).
    ``prefill-stage``         ``PrefillWorker._prefill_one`` raises — the
                              worker publishes an error handoff and the
                              decode side releases the staged slot+pages.
    ``handoff-import``        staged KV replaced with an unimportable
                              payload — ``_consume_handoffs`` containment
                              releases slot, suffix pages, prefix pins.
    ``journal-record``        ``ResumeJournal.record`` raises — the fleet
                              submit fails before any entry exists; depth
                              stays zero (the PR 16 leak class).
    ========================  =============================================

    ``boundaries()`` returns the subset applicable to the batcher's
    configuration (paged? radix? adapters? disaggregated? fleet engine?),
    so one parametrized test sweeps every layout without dead arms.
    """

    POISON = "leaksweep-poisoned-kv"

    def __init__(self, batcher: Any, engine: Any = None):
        self.batcher = batcher
        self.engine = engine
        self.fired = 0
        self._lock = threading.Lock()
        self._shots = 0
        self._restore: List[Any] = []  # (obj, attr, original)

    # -- boundary catalog ----------------------------------------------
    def boundaries(self) -> List[str]:
        b, out = self.batcher, []
        if getattr(b, "_adapters", None) is not None:
            out.append("adapter-pin")
        if getattr(b, "paged", False):
            out.append("page-alloc")
            if getattr(b, "_radix", None) is not None:
                out.append("radix-cow")
        if getattr(b, "_remote", None) is not None:
            out.append("prefill-stage")
            out.append("handoff-import")
        if self.engine is not None and getattr(self.engine, "_journal",
                                               None) is not None:
            out.append("journal-record")
        return out

    # -- one-shot plumbing ---------------------------------------------
    def _take_shot(self) -> bool:
        with self._lock:
            if self._shots <= 0:
                return False
            self._shots -= 1
            self.fired += 1
            return True

    def _wrap(self, obj: Any, attr: str, wrapper: Callable) -> None:
        original = getattr(obj, attr)
        setattr(obj, attr, wrapper(original))
        self._restore.append((obj, attr, original))

    def disarm(self) -> None:
        """Restore every wrapped method (idempotent)."""
        while self._restore:
            obj, attr, original = self._restore.pop()
            setattr(obj, attr, original)
        with self._lock:
            self._shots = 0

    def arm(self, boundary: str, shots: int = 1) -> "LeakSweep":
        """Install the one-shot fault for ``boundary``; returns self."""
        if boundary not in self.boundaries():
            raise ValueError(
                f"boundary {boundary!r} not applicable here "
                f"(have: {self.boundaries()})")
        self.disarm()
        with self._lock:
            self._shots = int(shots)
        getattr(self, "_arm_" + boundary.replace("-", "_"))()
        return self

    def _arm_adapter_pin(self) -> None:
        reg = self.batcher._adapters

        def wrapper(real):
            def resolve_and_pin(name):
                if name and self._take_shot():
                    raise KeyError(
                        f"leaksweep: injected adapter fault for {name!r}")
                return real(name)
            return resolve_and_pin

        self._wrap(reg, "resolve_and_pin", wrapper)

    def _arm_page_alloc(self) -> None:
        # while armed EVERY _alloc_pages call fails: the admission must
        # take its exhaustion unwind (shed or park), not the trie-evict
        # relief retry. Shots gate how many admissions see exhaustion.
        def wrapper(real):
            def _alloc_pages(n):
                if self._take_shot():
                    return None
                return real(n)
            return _alloc_pages

        self._wrap(self.batcher, "_alloc_pages", wrapper)

    def _arm_radix_cow(self) -> None:
        # identical injection point, but the driver arms exactly ONE shot
        # and sends a partial-block prefix continuation: the first
        # (cow-inclusive) allocation fails, the cow pin is dropped, and
        # the retry allocation succeeds — the admission completes.
        self._arm_page_alloc()

    def _arm_prefill_stage(self) -> None:
        from seldon_core_tpu.contracts.payload import SeldonError as _Err

        for worker in self.batcher._remote.workers:
            def wrapper(real):
                def _prefill_one(req):
                    if self._take_shot():
                        raise _Err("leaksweep: injected prefill fault",
                                   status_code=503, reason="INJECTED_FAULT")
                    return real(req)
                return _prefill_one

            self._wrap(worker, "_prefill_one", wrapper)

    def _arm_handoff_import(self) -> None:
        for worker in self.batcher._remote.workers:
            def wrapper(real):
                def _prefill_one(req):
                    h = real(req)
                    if self._take_shot():
                        h.staged = self.POISON
                    return h
                return _prefill_one

            self._wrap(worker, "_prefill_one", wrapper)

    def _arm_journal_record(self) -> None:
        from seldon_core_tpu.contracts.payload import SeldonError as _Err

        journal = self.engine._journal

        def wrapper(real):
            def record(entry):
                if self._take_shot():
                    raise _Err("leaksweep: injected journal fault",
                               status_code=503, reason="INJECTED_FAULT")
                return real(entry)
            return record

        self._wrap(journal, "record", wrapper)

    # -- residue probe --------------------------------------------------
    def residue(self) -> dict:
        """Every refcount the unwind paths are responsible for, as a
        dict that must be ALL ZEROS at idle. Cached trie blocks are a
        cache, not a leak — ``slot_pages`` subtracts them, and a leaked
        PIN shows up as ``shared_pins`` (a cached page with refcount
        still > 1 while no slot references it)."""
        b = self.batcher
        out = {}
        if getattr(b, "paged", False):
            _, in_use, _ = b._allocator.stats()
            cached = 0
            shared_pins = 0
            if b._radix is not None:
                rs = b._radix.stats()
                cached = rs["prefix_cached_blocks"]
                shared_pins = rs["prefix_shared_pages"]
            out["slot_pages"] = in_use - cached
            out["shared_pins"] = shared_pins
        if getattr(b, "_adapters", None) is not None:
            out["adapter_pins"] = sum(
                b._adapters.stats()["adapter_pins"].values())
        if getattr(b, "_remote", None) is not None:
            out["staged_jobs"] = len(b._remote_jobs)
            out["ready_handoffs"] = b._transfer.ready_depth()
        if self.engine is not None and getattr(self.engine, "_journal",
                                               None) is not None:
            out["journal_depth"] = self.engine._journal.depth()
        return out

    def assert_clean(self, context: str = "") -> None:
        leaks = {k: v for k, v in self.residue().items() if v != 0}
        if leaks:
            where = f" after {context}" if context else ""
            raise AssertionError(f"leak residue{where}: {leaks}")

    # -- the sweep ------------------------------------------------------
    def sweep(self, drive: Callable[[str], None],
              boundaries: Optional[Sequence[str]] = None) -> List[str]:
        """Arm each boundary in turn, let ``drive(boundary)`` push one
        request through the fault, then disarm and assert zero residue.
        Returns the boundaries actually swept (whose fault FIRED — a
        boundary the drive never reached raises, so a sweep cannot
        silently skip a layer)."""
        swept = []
        for boundary in (boundaries or self.boundaries()):
            before = self.fired
            self.arm(boundary)
            try:
                drive(boundary)
            finally:
                self.disarm()
            if self.fired == before:
                raise AssertionError(
                    f"leaksweep: fault at {boundary!r} never fired — "
                    f"the drive did not reach this boundary")
            self.assert_clean(context=boundary)
            swept.append(boundary)
        return swept


class DispatchFailer:
    """Scripted dispatch-level failure for a replica's BatcherService:
    wraps ``submit_sync`` so call *i* consults ``schedule[i]`` before
    delegating — the repeated-failure shape that trips the fleet's
    per-replica breaker (consecutive dispatch failures) without ever
    touching the batcher loop. Latency entries advance the FaultClock, so
    breaker reset windows can elapse in zero wall time."""

    def __init__(self, service: Any, schedule: FaultSchedule,
                 clock: Optional[FaultClock] = None):
        self.schedule = schedule
        self.clock = clock
        self.calls = 0
        self._real = service.submit_sync
        self._lock = threading.Lock()
        service.submit_sync = self._submit_sync

    def _submit_sync(self, *args, **kwargs):
        with self._lock:
            spec = self.schedule[self.calls]
            self.calls += 1
        if spec.latency_s and self.clock is not None:
            self.clock.advance(spec.latency_s)
        if spec.error is not None:
            raise spec.error
        return self._real(*args, **kwargs)
