"""Deterministic thread-interleaving harness (racelint's dynamic half).

The static analysis (tools/racelint) *claims* an access can interleave
with a guarded writer; this module lets a test *prove* it — or prove the
fix — by running real code under a virtual scheduler that decides every
context switch, records the decision sequence, and replays it exactly.

How it works
------------
Each task spawned on a :class:`DeterministicScheduler` runs in a real
``threading.Thread``, but only ONE thread is ever runnable: every traced
thread installs a ``sys.settrace`` hook that, at each preemption point
(every line — or every BYTECODE for ``granularity="opcode"``, which is
what catches ``x += 1`` lost updates: the preemption lands between the
LOAD and the STORE), parks the thread and hands control back to the
scheduler. The scheduler picks the next thread from

- a **recorded schedule** (exact replay),
- a **seeded RNG** (deterministic chaos: same seed, same interleaving),
- or the **lowest-index runnable** (the canonical schedule the
  :func:`explore` DFS perturbs).

Execution is fully serialized, so given the same code and the same
choice sequence the run is bit-for-bit deterministic. A thread that
blocks inside a real ``threading.Lock`` simply stops reporting back; the
scheduler notices, marks it BLOCKED, and schedules someone else — when
the lock is released the thread re-parks at its next preemption point
and rejoins the runnable set. If every live thread is BLOCKED, that is a
real deadlock and :class:`DeadlockError` reports it (this is how a
racelint ``lock-order-inversion`` finding is demonstrated, not just
asserted).

Time is the existing :class:`~seldon_core_tpu.testing.faults.FaultClock`:
the scheduler owns one and hands it to the code under test (breaker
reset timeouts, deadlines), so timed state machines advance by explicit
``scheduler.clock.advance(...)`` — never wall time.

Typical race hunt (tests/test_schedules.py)::

    def scenario(sched):
        adm = AdmissionController(max_inflight=1)
        sched.spawn(hammer, adm, name="t0")
        sched.spawn(hammer, adm, name="t1")
        return adm

    bad = find_race(scenario, lambda adm: adm.shed_total == 2,
                    granularity="opcode", max_schedules=300)
    # bad is None once the code is fixed; pre-fix it is a replayable
    # RecordedSchedule whose .choices pin the exact interleaving.
"""

from __future__ import annotations

import sys
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from seldon_core_tpu.testing.faults import FaultClock

# thread states
_NEW = "new"
_READY = "ready"        # parked at a preemption point, waiting for the token
_RUNNING = "running"    # holds the token
_BLOCKED = "blocked"    # granted the token but never reported back (real lock)
_DONE = "done"


class DeadlockError(RuntimeError):
    """Every live thread is blocked on a real synchronization primitive."""


class ScheduleDivergence(RuntimeError):
    """A replayed schedule named a thread that is not runnable — the code
    under test changed since the schedule was recorded."""


@dataclass
class RecordedSchedule:
    """The replayable artifact of one run: at each preemption point, which
    thread ran (``choices``) and which were runnable (``choice_sets`` —
    the DFS's branching structure). JSON-friendly on purpose: a failing
    schedule can be pinned into a regression test as a list of names."""

    choices: List[str] = field(default_factory=list)
    choice_sets: List[List[str]] = field(default_factory=list)
    steps: int = 0
    deadlocked: bool = False

    def to_list(self) -> List[str]:
        return list(self.choices)


class _Task:
    __slots__ = ("name", "fn", "args", "kwargs", "thread", "state", "gate",
                 "error", "result")

    def __init__(self, name, fn, args, kwargs):
        self.name = name
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.thread: Optional[threading.Thread] = None
        self.state = _NEW
        self.gate = threading.Event()
        self.error: Optional[BaseException] = None
        self.result: Any = None


class DeterministicScheduler:
    """One virtual-scheduler run. Construct, ``spawn`` tasks, ``run()``.

    Parameters
    ----------
    seed:        pick threads via ``random.Random(seed)`` (deterministic).
    schedule:    a recorded choice list (or RecordedSchedule) to replay
                 exactly; after it is exhausted, scheduling falls back to
                 lowest-index runnable.
    granularity: ``"line"`` or ``"opcode"`` — opcode-level preemption is
                 what interleaves WITHIN ``x += 1``.
    trace_filter: predicate(filename) choosing which code is preemptible.
                 Default: files under the ``seldon_core_tpu`` package plus
                 the spawned function's own module (so test-local replicas
                 of historical bugs are traced too).
    max_steps:   hard cap on preemption points (livelock backstop).
    clock:       a FaultClock (a fresh one by default) — hand it to the
                 code under test.
    stall_s:     how long the scheduler waits for a granted thread to
                 report back before declaring it BLOCKED. Lock-induced
                 blocking is a function of the schedule, so the choice
                 sequence is machine-independent as long as every traced
                 step finishes within stall_s; a step that outruns it
                 (GC pause, cold import inside the code under test) can
                 shift one choice point. Replays tolerate this: a forced
                 thread that is slow rather than lock-blocked gets a
                 grace window to park before divergence is declared.
    """

    def __init__(
        self,
        seed: Optional[int] = None,
        schedule: Optional[Any] = None,
        granularity: str = "line",
        trace_filter: Optional[Callable[[str], bool]] = None,
        max_steps: int = 200_000,
        clock: Optional[FaultClock] = None,
        stall_s: float = 0.2,
    ):
        if granularity not in ("line", "opcode"):
            raise ValueError("granularity must be 'line' or 'opcode'")
        if seed is not None:
            import random

            self._rng: Optional[Any] = random.Random(seed)
        else:
            self._rng = None
        if isinstance(schedule, RecordedSchedule):
            schedule = schedule.to_list()
        self._forced: List[str] = list(schedule or [])
        self.granularity = granularity
        self.trace_filter = trace_filter
        self.max_steps = int(max_steps)
        self.clock = clock if clock is not None else FaultClock()
        self.stall_s = float(stall_s)
        self.record = RecordedSchedule()
        self._tasks: List[_Task] = []
        self._by_thread: Dict[int, _Task] = {}
        self._mu = threading.Lock()
        self._wake = threading.Condition(self._mu)
        self._traced_files: set = set()
        self._started = False
        self._last: Optional[str] = None

    # -- task management -----------------------------------------------
    def spawn(self, fn: Callable, *args, name: Optional[str] = None,
              **kwargs) -> str:
        if self._started:
            raise RuntimeError("spawn() before run(): the schedule space "
                               "must be fixed up front for replay to work")
        name = name or f"t{len(self._tasks)}"
        if any(t.name == name for t in self._tasks):
            raise ValueError(f"duplicate task name {name!r}")
        code = getattr(fn, "__code__", None)
        if code is not None:
            self._traced_files.add(code.co_filename)
        self._tasks.append(_Task(name, fn, args, kwargs))
        return name

    def results(self) -> Dict[str, Any]:
        return {t.name: t.result for t in self._tasks}

    def errors(self) -> Dict[str, BaseException]:
        return {t.name: t.error for t in self._tasks if t.error is not None}

    # -- tracing --------------------------------------------------------
    def _should_trace(self, filename: str) -> bool:
        if self.trace_filter is not None:
            return self.trace_filter(filename)
        return filename in self._traced_files or (
            ("seldon_core_tpu" in filename) and "testing" not in filename)

    def _trace(self, frame, event, arg):
        if event != "call":
            return None
        if not self._should_trace(frame.f_code.co_filename):
            return None
        if self.granularity == "opcode":
            frame.f_trace_opcodes = True
        return self._local_trace

    def _local_trace(self, frame, event, arg):
        if event == ("opcode" if self.granularity == "opcode" else "line"):
            self._preempt()
        return self._local_trace

    # -- thread side ----------------------------------------------------
    def _bootstrap(self, task: _Task):
        # self-registration BEFORE the first traced frame: _preempt looks
        # the task up by thread ident, and the spawner cannot know the
        # ident until after start() — registering there races the thread
        # reaching its first preemption point
        self._by_thread[threading.get_ident()] = task
        sys.settrace(self._trace)
        try:
            task.result = task.fn(*task.args, **task.kwargs)
        except BaseException as e:  # noqa: BLE001 — surfaced via errors()
            task.error = e
        finally:
            sys.settrace(None)
            with self._mu:
                task.state = _DONE
                self._wake.notify_all()

    def _preempt(self):
        task = self._by_thread.get(threading.get_ident())
        if task is None:
            return
        with self._mu:
            task.state = _READY
            task.gate.clear()
            self._wake.notify_all()
        task.gate.wait()

    # -- scheduler side -------------------------------------------------
    def _pick(self, ready: List[_Task]) -> _Task:
        names = [t.name for t in ready]
        i = len(self.record.choices)
        if i < len(self._forced):
            want = self._forced[i]
            for t in ready:
                if t.name == want:
                    self._note(t, names)
                    return t
            # The forced thread may just be SLOW (marked BLOCKED because a
            # traced step outran stall_s on a loaded machine) rather than
            # truly lock-blocked: give it a grace window to park before
            # declaring the prefix infeasible, so replays are not
            # wall-clock sensitive. A genuinely lock-blocked thread cannot
            # park here — its holder is parked waiting for this decision —
            # so the wait expires and the divergence is real.
            alive = any(t.name == want and t.state != _DONE
                        for t in self._tasks)
            if alive:
                deadline = self._now() + max(self.stall_s * 4, 0.4)
                while self._now() < deadline:
                    self._wake.wait(self.stall_s)
                    for t in self._tasks:
                        if t.name == want and t.state == _READY:
                            self._note(t, [t.name])
                            return t
            raise ScheduleDivergence(
                f"replay step {i}: schedule says {want!r} but runnable "
                f"threads are {names} — the code under test no longer "
                "matches the recording (or the prefix is infeasible "
                "under this code's lock states)")
        if self._rng is not None:
            t = self._rng.choice(ready)
        else:
            # canonical default: INERTIA — keep running the thread that ran
            # last (CHESS-style preemption bounding). Each forced flip in a
            # DFS prefix is then exactly one preemption, so the classic
            # lost-update interleaving (A loads, B runs to completion, A
            # stores) is reachable with a single flip instead of a deep
            # chain of them.
            t = None
            if self._last is not None:
                for cand in ready:
                    if cand.name == self._last:
                        t = cand
                        break
            if t is None:
                t = ready[0]  # lowest spawn index
        self._note(t, names)
        return t

    def _note(self, task: _Task, names: List[str]):
        self.record.choices.append(task.name)
        self.record.choice_sets.append(names)
        self._last = task.name

    def run(self) -> RecordedSchedule:
        """Drive every task to completion (or deadlock). Returns the
        recorded schedule; task exceptions are collected in ``errors()``
        (assertion failures inside tasks are NOT re-raised here — race
        tests usually assert on shared state afterwards)."""
        self._started = True
        for task in self._tasks:
            task.thread = threading.Thread(
                target=self._bootstrap, args=(task,),
                name=f"sched-{task.name}", daemon=True)
        with self._mu:
            for task in self._tasks:
                task.state = _READY  # parked "before the first line"
        for task in self._tasks:
            task.thread.start()
        # No quiesce wait needed: every task is READY up front ("parked
        # before its first line"), so the first grant means "run from the
        # top to the first preemption point" — Event semantics make an
        # early gate.set() safe even if the thread has not parked yet.
        while True:
            with self._mu:
                live = [t for t in self._tasks if t.state not in (_DONE,)]
                if not live:
                    break
                ready = [t for t in self._tasks if t.state == _READY]
                if not ready:
                    # grace period: a BLOCKED thread whose lock was just
                    # released by the previous grant needs a moment to wake
                    # from the kernel wait and park at its next preemption
                    # point — declaring deadlock instantly would be a false
                    # positive. A real deadlock pays this wait once.
                    deadline = self._now() + max(self.stall_s * 4, 0.2)
                    while self._now() < deadline:
                        self._wake.wait(self.stall_s)
                        ready = [t for t in self._tasks if t.state == _READY]
                        live = [t for t in self._tasks if t.state != _DONE]
                        if ready or not live:
                            break
                    if not live:
                        break
                if not ready:
                    blocked = [t.name for t in live]
                    self.record.deadlocked = True
                    raise DeadlockError(
                        f"all live threads blocked on real sync primitives: "
                        f"{blocked} after {self.record.steps} steps — a "
                        "lock cycle or a wait nobody will signal")
                if self.record.steps >= self.max_steps:
                    raise RuntimeError(
                        f"schedule exceeded max_steps={self.max_steps} "
                        "(livelock, or raise the cap)")
                task = self._pick(ready)
                task.state = _RUNNING
                self.record.steps += 1
                task.gate.set()
                # wait for the granted thread to park again, finish, or
                # stop reporting (=> blocked on a real primitive)
                deadline = self._now() + self.stall_s
                while task.state == _RUNNING:
                    remaining = deadline - self._now()
                    if remaining <= 0:
                        # stopped reporting: blocked inside a real lock.
                        # When the holder releases it, the thread runs to
                        # its next preemption point and flips itself back
                        # to READY in _preempt().
                        task.state = _BLOCKED
                        break
                    self._wake.wait(remaining)
        return self.record

    def _now(self) -> float:
        import time

        return time.monotonic()

def run_schedule(scenario: Callable[[DeterministicScheduler], Any],
                 schedule: Optional[Sequence[str]] = None,
                 seed: Optional[int] = None,
                 granularity: str = "line",
                 max_steps: int = 200_000,
                 clock: Optional[FaultClock] = None,
                 stall_s: float = 0.2):
    """One scheduled run. ``scenario(sched)`` spawns tasks and returns the
    shared object under test; returns ``(shared, record, sched)``."""
    sched = DeterministicScheduler(
        seed=seed, schedule=list(schedule) if schedule else None,
        granularity=granularity, max_steps=max_steps, clock=clock,
        stall_s=stall_s)
    shared = scenario(sched)
    record = sched.run()
    return shared, record, sched


def explore(scenario: Callable[[DeterministicScheduler], Any],
            max_schedules: int = 200,
            granularity: str = "line",
            max_steps: int = 200_000,
            stall_s: float = 0.2):
    """Bounded DFS over the interleaving space (stateless model checking).

    Runs the canonical schedule first, then systematically perturbs the
    earliest-yet-unperturbed choice point: for each recorded decision
    with >1 runnable thread, re-runs with the prefix forced to each
    alternative. Yields ``(shared, record, sched)`` per schedule, at most
    ``max_schedules`` of them. Exhaustive when the space is smaller than
    the budget; a breadth-leaning sample otherwise.
    """
    tried: set = set()
    frontier: List[List[str]] = [[]]
    produced = 0
    while frontier and produced < max_schedules:
        prefix = frontier.pop(0)
        key = tuple(prefix)
        if key in tried:
            continue
        tried.add(key)
        sched = DeterministicScheduler(
            schedule=prefix, granularity=granularity, max_steps=max_steps,
            stall_s=stall_s)
        shared = scenario(sched)
        try:
            record = sched.run()
        except DeadlockError:
            record = sched.record
        except ScheduleDivergence:
            # infeasible prefix: the forced thread is lock-blocked at that
            # point in THIS interleaving (prefixes are recorded from runs
            # with different lock states). Not an error — just a branch
            # that does not exist; count it against the budget and move on.
            produced += 1
            continue
        produced += 1
        yield shared, record, sched
        # expand: alternatives at every choice point from len(prefix) on
        for i in range(len(prefix), len(record.choices)):
            options = record.choice_sets[i]
            if len(options) <= 1:
                continue
            for alt in options:
                if alt == record.choices[i]:
                    continue
                frontier.append(record.choices[:i] + [alt])


def find_race(scenario: Callable[[DeterministicScheduler], Any],
              invariant: Callable[[Any], bool],
              max_schedules: int = 200,
              granularity: str = "line",
              max_steps: int = 200_000,
              stall_s: float = 0.2) -> Optional[RecordedSchedule]:
    """Search the bounded schedule space for an interleaving that violates
    ``invariant(shared)`` (or errors/deadlocks a task). Returns the first
    failing RecordedSchedule — replay it with
    ``run_schedule(scenario, schedule=found.to_list())`` — or None if
    every explored schedule upholds the invariant."""
    for shared, record, sched in explore(
            scenario, max_schedules=max_schedules, granularity=granularity,
            max_steps=max_steps, stall_s=stall_s):
        if record.deadlocked or sched.errors() or not invariant(shared):
            return record
    return None
