"""Small shared helpers with no heavier home."""

from __future__ import annotations

from typing import Sequence


def bucket(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= n; beyond the largest bucket, round up to a
    multiple of it (bounded compile count) instead of silently truncating —
    any hard cap (model context, cache length) is applied by callers. The
    single bucketing policy for prompt lengths (servers/llmserver.py) and
    detector window counts (analytics/outliers.py)."""
    for b in buckets:
        if n <= b:
            return b
    top = buckets[-1]
    return ((n + top - 1) // top) * top
