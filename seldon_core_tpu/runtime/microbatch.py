"""Cross-request micro-batching for row-wise predictor graphs.

The BASELINE.json north star: "the orchestrator's gRPC request batcher shards
inference-graph traffic across a v5e slice". Concurrent predict requests are
coalesced into ONE padded device batch — XLA then runs one large MXU-friendly
computation (optionally sharded over the mesh via the model's own
data-parallel sharding) instead of many tiny ones, which is where TPU
throughput comes from.

Correctness precondition: the graph must be *row-wise* — every component maps
row i of its input to row i of its output independently (MODELs,
TRANSFORMERs, COMBINERs are; ROUTERs are not, because a routing decision made
for a merged batch would apply one branch to every caller's rows). The
constructor walks the graph and refuses routing graphs.

Requests are grouped by feature shape (rows concat only when the non-batch
dims agree); each group flushes when it reaches ``max_batch`` rows or the
oldest request has waited ``max_delay_ms``.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from seldon_core_tpu.contracts.graph import UnitMethod
from seldon_core_tpu.contracts.payload import SeldonError, SeldonMessage

logger = logging.getLogger(__name__)


class _Pending:
    __slots__ = ("msg", "rows", "future", "t0")

    def __init__(self, msg: SeldonMessage, rows: np.ndarray, future: asyncio.Future):
        self.msg = msg
        self.rows = rows
        self.future = future
        self.t0 = time.monotonic()


def _graph_is_rowwise(spec) -> Tuple[bool, str]:
    stack = [spec.graph]
    while stack:
        unit = stack.pop()
        if UnitMethod.ROUTE in unit.resolved_methods():
            return False, f"unit {unit.name!r} routes per request"
        stack.extend(unit.children)
    return True, ""


class MicroBatcher:
    """Wraps a GraphEngine (or anything with async ``predict``/``send_feedback``)
    with cross-request batching. Drop-in for the REST/gRPC engine apps."""

    def __init__(
        self,
        engine: Any,
        max_batch: int = 64,
        max_delay_ms: float = 2.0,
        strict: bool = True,
    ):
        spec = getattr(engine, "spec", None)
        if spec is not None:
            ok, why = _graph_is_rowwise(spec)
            if not ok:
                if strict:
                    raise SeldonError(
                        f"MicroBatcher needs a row-wise graph: {why}", reason="BAD_GRAPH"
                    )
                logger.warning("micro-batching disabled: %s", why)
                self._passthrough = True
            else:
                self._passthrough = False
        else:
            self._passthrough = False
        self.engine = engine
        self.spec = spec
        self.max_batch = int(max_batch)
        self.max_delay_s = float(max_delay_ms) / 1000.0
        self._groups: Dict[Tuple, List[_Pending]] = {}
        self._flusher: Optional[asyncio.Task] = None
        # observability
        self.batches = 0
        self.batched_requests = 0

    # ------------------------------------------------------------------
    async def predict(self, request: SeldonMessage) -> SeldonMessage:
        if self._passthrough:
            return await self.engine.predict(request)
        payload = request.payload() if request.data is not None else None
        if not isinstance(payload, np.ndarray) or payload.ndim < 1:
            # bytes/str/json or scalar payloads pass through unbatched
            return await self.engine.predict(request)
        rows = np.atleast_2d(payload)
        # names are part of the key so requests with different feature names
        # are never merged (group[0]'s names label the merged batch)
        key = (rows.shape[1:], str(rows.dtype), request.which, tuple(request.names or ()))
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        group = self._groups.setdefault(key, [])
        group.append(_Pending(request, rows, fut))
        if sum(p.rows.shape[0] for p in group) >= self.max_batch:
            await self._flush(key)
        else:
            self._ensure_flusher()
        return await fut

    async def send_feedback(self, feedback) -> SeldonMessage:
        return await self.engine.send_feedback(feedback)

    # ------------------------------------------------------------------
    def _ensure_flusher(self):
        if self._flusher is None or self._flusher.done():
            self._flusher = asyncio.get_running_loop().create_task(self._flush_loop())

    async def _flush_loop(self):
        while self._groups:
            now = time.monotonic()
            due = [
                key
                for key, group in self._groups.items()
                if group and now - group[0].t0 >= self.max_delay_s
            ]
            for key in due:
                await self._flush(key)
            await asyncio.sleep(self.max_delay_s / 4 if self._groups else 0)

    async def _flush(self, key):
        # Deadline hygiene: the flusher task inherits the contextvar context
        # of whichever request first created it, and an inline flush runs in
        # the triggering request's context. Either way a single request's
        # deadline must not govern (or, worse, permanently poison) merged
        # batches — execute them deadline-free. Per-row deadlines are not
        # differentiated inside a merged batch (docs/resilience.md).
        from seldon_core_tpu.runtime.resilience import deadline_scope

        group = self._groups.pop(key, [])
        if not group:
            return
        if len(group) == 1:
            p = group[0]
            try:
                with deadline_scope(None):
                    p.future.set_result(await self.engine.predict(p.msg))
            except Exception as e:
                if not p.future.done():
                    p.future.set_exception(e)
            return

        merged_rows = np.concatenate([p.rows for p in group], axis=0)
        names = group[0].msg.names
        merged = SeldonMessage.from_array(merged_rows, names=list(names) if names else None)
        self.batches += 1
        self.batched_requests += len(group)
        try:
            with deadline_scope(None):
                out = await self.engine.predict(merged)
        except Exception as e:
            for p in group:
                if not p.future.done():
                    p.future.set_exception(e)
            return

        try:
            out_payload = out.payload()
            splittable = (
                isinstance(out_payload, np.ndarray)
                and out_payload.ndim >= 1
                and out_payload.shape[0] == merged_rows.shape[0]
            )
            offset = 0
            for p in group:
                n = p.rows.shape[0]
                if splittable:
                    part = np.atleast_2d(out_payload)[offset : offset + n]
                    resp = SeldonMessage.from_array(part, names=out.names or None)
                    resp.meta = out.meta.copy()
                else:
                    # non-row-wise output (shouldn't happen for validated
                    # graphs): every caller gets its own deep copy of the full
                    # response so the per-caller puid below doesn't clobber a
                    # shared object
                    resp = SeldonMessage.from_dict(out.to_dict())
                # unique puid per caller, as the engine would have assigned
                from seldon_core_tpu.runtime.engine import make_puid

                resp.meta.puid = p.msg.meta.puid or make_puid()
                offset += n
                if not p.future.done():
                    p.future.set_result(resp)
        except Exception as e:
            for p in group:
                if not p.future.done():
                    p.future.set_exception(e)
