"""Speculative-decoding host-side state: mode normalization and the
per-slot draft-length controller.

The device half of speculation lives in ``LLMServer._get_spec_step`` (the
fused draft+verify program) and ``ContinuousBatcher`` (dispatch/drain of
variable-advance steps). This module is deliberately jax-free: the
controller is pure bookkeeping shared between the batcher loop's worker
threads (observe at drain, cap at dispatch) and transport threads
(``llm_stats`` snapshots at /metrics scrape time), so it is modeled by
racelint's concurrency analysis and proven by the deterministic-schedule
suite (tests/test_schedules.py) without pulling in an accelerator stack.
"""

from __future__ import annotations

import threading
from typing import List

SPEC_MODES = ("off", "ngram", "draft")

# draft tokens per verify step when speculation is on and no explicit
# spec_k was configured (the verify forward is K+1 tokens wide: the last
# accepted token plus K drafts)
DEFAULT_SPEC_K = 4

# longest n-gram the self-draft proposer tries to match in the slot's
# prompt+generated history (it falls through to shorter grams down to 1)
DEFAULT_SPEC_NGRAM = 3


def normalize_spec_mode(value) -> str:
    """Canonical spec_mode ("off", "ngram" or "draft"); raises ValueError on
    anything else so misconfiguration fails at load() time, not inside the
    batcher's dispatch loop."""
    v = str(value or "off").strip().lower()
    if v in ("off", "none", "no", "0", ""):
        return "off"
    if v in ("ngram", "n-gram", "prompt-lookup", "prompt_lookup", "self"):
        return "ngram"
    if v in ("draft", "draft-model", "draft_model", "model"):
        return "draft"
    raise ValueError(
        f"unknown spec_mode {value!r}: expected one of {SPEC_MODES}")


class SpecController:
    """Per-slot draft-length controller: adapts the number of draft tokens
    K offered to the verify step to the acceptance rate that slot has been
    observing, so a slot decoding un-draftable text stops paying for K
    rejected drafts per forward while a repetitive slot keeps the full
    depth.

    Every state transition happens under ``self._lock``: ``observe`` runs
    on the batcher loop's drain worker thread, ``cap`` on its dispatch
    worker thread, ``reset`` at admission, and ``rates``/``snapshot`` on
    transport threads at /metrics scrape time — an unlocked EMA update is
    a read-modify-write that loses observations under exactly the
    interleavings tests/test_schedules.py explores."""

    # EMA weight of the newest observation; small enough that one lucky
    # block does not whipsaw the cap, large enough to adapt within ~10
    # verify steps
    ALPHA = 0.3
    # verify steps a fresh slot runs at full depth before the controller
    # trusts its EMA (a single early rejection must not strand a
    # repetitive slot at cap 1)
    WARMUP_STEPS = 2

    def __init__(self, slots: int, k: int):
        self.S = int(slots)
        self.k = int(k)
        self._lock = threading.Lock()
        self._rate = [1.0] * self.S     # per-slot acceptance-rate EMA
        self._steps = [0] * self.S      # verify steps observed this occupancy
        self._accepted_total = 0        # drafts accepted, lifetime
        self._drafted_total = 0         # drafts offered, lifetime
        # per-slot verify steps, lifetime: one per ACTIVE SLOT per drained
        # verify forward (a forward covering 8 slots adds 8 — divide by
        # the active-slot count for the program count)
        self._slot_steps_total = 0
        self._tokens_total = 0          # tokens emitted by verify forwards

    def reset(self, slot: int) -> None:
        """New occupant: forget the previous request's acceptance history
        (its text is gone; its rate says nothing about the newcomer)."""
        with self._lock:
            self._rate[slot] = 1.0
            self._steps[slot] = 0

    def observe(self, slot: int, accepted_drafts: int, offered: int,
                tokens: int) -> None:
        """One drained verify step for ``slot``: ``accepted_drafts`` of
        ``offered`` draft tokens survived verification and the forward
        emitted ``tokens`` (accepted drafts + the corrected/bonus sample)."""
        with self._lock:
            self._slot_steps_total += 1
            self._tokens_total += int(tokens)
            self._accepted_total += int(accepted_drafts)
            self._drafted_total += int(offered)
            self._steps[slot] += 1
            if offered > 0:
                r = accepted_drafts / float(offered)
                self._rate[slot] += self.ALPHA * (r - self._rate[slot])

    def cap(self, slot: int) -> int:
        """Draft tokens to offer this slot on the next verify step. Full
        depth during warmup, then stepped down with the acceptance EMA.
        The floor is 1, NOT 0: a zero cap stops producing observations
        (nothing offered, nothing to accept), so the EMA could never
        recover when un-draftable text turns draftable — e.g. greedy
        decode falling into a cycle after a non-matching prompt. One
        probe draft per forward is the cheapest signal that keeps the
        controller live, and its reject costs a single wasted token
        column."""
        with self._lock:
            if self._steps[slot] < self.WARMUP_STEPS:
                return self.k
            r = self._rate[slot]
        if r >= 0.5:
            return self.k
        if r >= 0.2:
            return max(self.k // 2, 1)
        return 1

    def rates(self) -> List[float]:
        """Per-slot acceptance-rate EMA snapshot (one consistent read)."""
        with self._lock:
            return list(self._rate)

    def snapshot(self) -> dict:
        """Lifetime aggregates for llm_stats / the benches: draft
        acceptance rate, accepted tokens per target forward (the
        >1-token-per-cache-read multiplier speculation exists to buy),
        and the draft-overhead fraction — the share of verify-forward
        token columns (offered drafts + the always-computed base column)
        whose compute was wasted on drafts that lost verification."""
        with self._lock:
            drafted = self._drafted_total
            steps = self._slot_steps_total
            # every slot's share of a verify forward computes offered+1
            # token columns for that slot
            columns = drafted + steps
            return {
                "spec_accept_rate": (
                    self._accepted_total / drafted if drafted else 0.0),
                # per SLOT-step: a slot's KV is read once per verify
                # forward, so this is tokens per cache read for that slot
                "spec_tokens_per_forward": (
                    self._tokens_total / steps if steps else 0.0),
                "spec_draft_overhead_fraction": (
                    (drafted - self._accepted_total) / columns
                    if columns else 0.0),
                "spec_slot_steps_total": steps,
                "spec_accepted_drafts_total": self._accepted_total,
                "spec_drafted_total": drafted,
                "spec_tokens_total": self._tokens_total,
            }
