"""Remote graph nodes: async client for components living in other processes.

The reference talks to every node this way (`engine/src/main/java/io/seldon/
engine/service/InternalPredictionService.java:186-443`: per-node REST/gRPC with
3 retries, timeouts from annotations). Here remote hops are the *exception* —
only units with an explicit endpoint — but the semantics match: same routes,
same payload schema, retry-with-backoff, per-call deadline.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Any, Optional, Sequence

from seldon_core_tpu.components.component import SeldonComponent
from seldon_core_tpu.contracts.graph import Endpoint, EndpointType
from seldon_core_tpu.contracts.payload import (
    Feedback,
    SeldonError,
    SeldonMessage,
    SeldonMessageList,
)
from seldon_core_tpu.runtime.resilience import DeadlineExceeded, current_deadline, effective_timeout
from seldon_core_tpu.tracing import current_traceparent

logger = logging.getLogger(__name__)

DEFAULT_RETRIES = 3  # reference default (`InternalPredictionService.java:84`)
DEFAULT_TIMEOUT_S = 5.0
# no separate connect deadline unless the annotation asks for one — the
# total timeout already bounds slow connects, and a default connect cap
# would break formerly-working slow-handshake deployments
DEFAULT_CONNECT_TIMEOUT_S = None

# the reference's per-deployment tuning annotations
# (`InternalPredictionService.java:82-91`, catalog doc/source/graph/annotations.md)
ANNOTATION_REST_READ_TIMEOUT = "seldon.io/rest-read-timeout"        # ms
ANNOTATION_REST_CONNECTION_TIMEOUT = "seldon.io/rest-connection-timeout"  # ms
ANNOTATION_REST_RETRIES = "seldon.io/rest-connect-retries"
ANNOTATION_GRPC_READ_TIMEOUT = "seldon.io/grpc-read-timeout"        # ms
# wire format for tensor payloads on this hop (codec/framing.py):
#   json  — today's proto-JSON, byte-for-byte (the default)
#   frame — binary frames both ways (requests framed when the message
#           carries tensor/binData payloads; falls back to JSON once if
#           the peer rejects frames, then stays on JSON)
#   auto  — JSON requests + Accept: application/x-seldon-frame, so an
#           updated peer may frame RESPONSES; safe against old peers
ANNOTATION_WIRE_FORMAT = "seldon.io/wire-format"

WIRE_FORMATS = ("json", "frame", "auto")


def config_from_annotations(annotations: Optional[dict]) -> dict:
    """Remote-call tuning from deployment annotations; missing/garbage
    values keep the defaults (same tolerance as the reference's parser)."""
    annotations = annotations or {}

    def ms(key: str, default_s: Optional[float]) -> Optional[float]:
        try:
            return float(annotations[key]) / 1000.0
        except (KeyError, TypeError, ValueError):
            return default_s

    try:
        retries = int(annotations[ANNOTATION_REST_RETRIES])
    except (KeyError, TypeError, ValueError):
        retries = DEFAULT_RETRIES
    wire_format = str(annotations.get(ANNOTATION_WIRE_FORMAT, "json") or
                      "json").strip().lower()
    if wire_format not in WIRE_FORMATS:
        wire_format = "json"
    return {
        "retries": max(retries, 1),
        "timeout_s": ms(ANNOTATION_REST_READ_TIMEOUT, DEFAULT_TIMEOUT_S),
        "connect_timeout_s": ms(ANNOTATION_REST_CONNECTION_TIMEOUT, DEFAULT_CONNECT_TIMEOUT_S),
        "grpc_timeout_s": ms(ANNOTATION_GRPC_READ_TIMEOUT, DEFAULT_TIMEOUT_S),
        "wire_format": wire_format,
    }


# Remote hops are small request/response JSON bodies on loopback or
# intra-cluster links: Nagle buffering on such writes adds up to an RTT of
# idle wait per hop for nothing (the round-5 loopback profile shows ~15 ms
# per engine->node hop, VERDICT weak #3). aiohttp in this tree does NOT set
# TCP_NODELAY on client sockets, so flip it at connection setup; keep-alive
# stays on (force_close=False) so sequential calls reuse one connection —
# tests/test_remote_keepalive.py pins both behaviours.
KEEPALIVE_TIMEOUT_S = 30.0


def _make_connector():
    """TCPConnector with TCP_NODELAY applied to every new connection and
    keep-alive long enough to survive inter-request gaps. Falls back to the
    stock connector if aiohttp's private connection hook moves."""
    import aiohttp

    try:
        from aiohttp.tcp_helpers import tcp_nodelay

        class _NoDelayConnector(aiohttp.TCPConnector):
            async def _wrap_create_connection(self, *args, **kwargs):
                transport, proto = await super()._wrap_create_connection(
                    *args, **kwargs)
                tcp_nodelay(transport, True)
                return transport, proto

        return _NoDelayConnector(keepalive_timeout=KEEPALIVE_TIMEOUT_S)
    except (ImportError, AttributeError):  # pragma: no cover - aiohttp drift
        logger.warning("aiohttp private API moved; remote hops run without "
                       "explicit TCP_NODELAY")
        return aiohttp.TCPConnector(keepalive_timeout=KEEPALIVE_TIMEOUT_S)


class RemoteComponent(SeldonComponent):
    """A graph node reached over the network; implements the *_raw contract so
    dispatch passes full messages through untouched."""

    is_async = True

    def __init__(
        self,
        endpoint: Endpoint,
        client: Optional[Any] = None,
        retries: int = DEFAULT_RETRIES,
        timeout_s: float = DEFAULT_TIMEOUT_S,
        connect_timeout_s: Optional[float] = DEFAULT_CONNECT_TIMEOUT_S,
        grpc_timeout_s: Optional[float] = None,
        annotations: Optional[dict] = None,
        wire_format: str = "json",
    ):
        super().__init__()
        self.endpoint = endpoint
        if annotations:
            cfg = config_from_annotations(annotations)
            retries = cfg["retries"]
            timeout_s = cfg["timeout_s"]
            connect_timeout_s = cfg["connect_timeout_s"]
            grpc_timeout_s = cfg["grpc_timeout_s"]
            if cfg["wire_format"] != "json":
                wire_format = cfg["wire_format"]
        if wire_format not in WIRE_FORMATS:
            raise ValueError(f"wire_format {wire_format!r}: expected one of "
                             f"{WIRE_FORMATS}")
        self.retries = retries
        self.timeout_s = timeout_s
        self.connect_timeout_s = connect_timeout_s
        self.grpc_timeout_s = grpc_timeout_s if grpc_timeout_s is not None else timeout_s
        self.wire_format = wire_format
        # latched when a peer rejects a framed request (old server): this
        # hop downgrades to JSON permanently instead of paying a rejected
        # round trip per call — the "JSON fallback" half of the contract
        self._frame_unsupported = False
        self._client = client
        # ClientSessions bind to the event loop they were created on; engines
        # may be driven from several short-lived loops (predict_sync), so keep
        # one session per live loop.
        self._sessions: dict = {}

    def load(self) -> None:
        pass

    # -- transport ------------------------------------------------------
    def _get_session(self):
        import aiohttp

        loop = asyncio.get_running_loop()
        session = self._sessions.get(id(loop))
        if session is None or session.closed or loop.is_closed():
            # drop sessions whose loops are gone
            self._sessions = {
                k: s for k, s in self._sessions.items() if not s.closed and k != id(loop)
            }
            session = aiohttp.ClientSession(connector=_make_connector())
            self._sessions[id(loop)] = session
        return session

    async def _rest_call(self, path: str, payload: Optional[dict], *,
                         frame: Optional[bytes] = None,
                         accept_frame: bool = False):
        """One REST hop. JSON request/response by default (``payload``);
        ``frame`` ships a binary frame body instead, and ``accept_frame``
        advertises that a framed RESPONSE is welcome. Returns the parsed
        JSON dict, or a decoded SeldonMessage when the peer responded
        with ``application/x-seldon-frame``."""
        import aiohttp

        from seldon_core_tpu.codec.framing import (
            CONTENT_TYPE_FRAME, decode_message)

        session = self._get_session()
        url = f"http://{self.endpoint.service_host}:{self.endpoint.service_port}{path}"
        # the active span's W3C traceparent rides every hop (and every
        # retry), so the remote node's own spans join this request's trace
        # — the reference's engine->node span chain (PAPER.md §5)
        tp = current_traceparent()
        headers = {"traceparent": tp} if tp else {}
        if accept_frame:
            headers["Accept"] = f"{CONTENT_TYPE_FRAME}, application/json"
        body_kw: dict = {"json": payload}
        if frame is not None:
            headers["Content-Type"] = CONTENT_TYPE_FRAME
            body_kw = {"data": frame}
        last_err: Optional[Exception] = None
        for attempt in range(self.retries):
            # each attempt (not just the first) is clamped to the remaining
            # request budget: retries never extend past the deadline, and an
            # exhausted budget raises 504 instead of starting network work
            hop_timeout = effective_timeout(self.timeout_s)
            try:
                async with session.post(
                    url,
                    headers=headers or None,
                    timeout=aiohttp.ClientTimeout(
                        total=hop_timeout, connect=self.connect_timeout_s
                    ),
                    **body_kw,
                ) as resp:
                    if resp.content_type == CONTENT_TYPE_FRAME:
                        raw = await resp.read()
                        if resp.status != 200:
                            raise SeldonError(
                                f"Remote node {url} returned {resp.status}",
                                status_code=resp.status,
                                reason="REMOTE_NODE_ERROR",
                            )
                        return decode_message(raw)
                    body = await resp.text()
                    if resp.status != 200:
                        raise SeldonError(
                            f"Remote node {url} returned {resp.status}: {body[:500]}",
                            status_code=resp.status,
                            reason="REMOTE_NODE_ERROR",
                        )
                    return json.loads(body)
            except (aiohttp.ClientError, asyncio.TimeoutError, json.JSONDecodeError) as e:
                last_err = e
                d = current_deadline()
                if d is not None and d.expired:
                    raise DeadlineExceeded(
                        f"deadline exceeded during remote hop to {url}: {e}"
                    ) from e
                if attempt + 1 < self.retries:
                    await asyncio.sleep(0.05 * (2**attempt))
        raise SeldonError(
            f"Remote node {url} unreachable after {self.retries} attempts: {last_err}",
            status_code=503,
            reason="REMOTE_NODE_UNAVAILABLE",
        )

    async def _grpc_call(self, method: str, request_msg: Any) -> SeldonMessage:
        from seldon_core_tpu.transport.grpc_client import unary_call

        tp = current_traceparent()
        return await unary_call(
            f"{self.endpoint.service_host}:{self.endpoint.service_port}",
            method,
            request_msg,
            timeout_s=effective_timeout(self.grpc_timeout_s),
            metadata=[("traceparent", tp)] if tp else None,
        )

    async def _call(self, rest_path: str, grpc_method: str, msg: Any) -> SeldonMessage:
        from seldon_core_tpu.codec.framing import (
            frameable, grpc_is_framed, grpc_unwrap, grpc_wrap)

        wf = self.wire_format if not self._frame_unsupported else "json"
        if self.endpoint.type == EndpointType.GRPC.value:
            if wf == "frame" and frameable(msg):
                # binData passthrough: the frame rides the proto binData
                # arm raw (proto never base64s bytes), tagged in meta so
                # the server can tell an envelope from user binData
                out = await self._grpc_call(grpc_method, grpc_wrap(msg))
                return grpc_unwrap(out) if grpc_is_framed(out) else out
            return await self._grpc_call(grpc_method, msg)
        if wf == "json":
            # byte-for-byte the pre-framing hop: same body, same headers
            out = await self._rest_call(rest_path, msg.to_dict())
            return SeldonMessage.from_dict(out)
        frame = None
        if wf == "frame" and frameable(msg):
            from seldon_core_tpu.codec.framing import encode_message

            frame = encode_message(msg, path="rest")
        try:
            out = await self._rest_call(
                rest_path, None if frame is not None else msg.to_dict(),
                frame=frame, accept_frame=True)
        except SeldonError as e:
            # an old peer 400/415s a framed request: fall back to JSON for
            # this call and latch the downgrade for the rest of this hop
            if frame is None or e.status_code not in (400, 415):
                raise
            logger.warning("peer %s rejected a framed request (%s); "
                           "downgrading this hop to JSON",
                           self.endpoint.service_host, e.status_code)
            self._frame_unsupported = True
            out = await self._rest_call(rest_path, msg.to_dict())
        if isinstance(out, SeldonMessage):
            return out
        return SeldonMessage.from_dict(out)

    async def close(self) -> None:
        for session in list(self._sessions.values()):
            if not session.closed:
                try:
                    await session.close()
                except RuntimeError:
                    pass  # session's loop already gone
        self._sessions.clear()

    # -- component contract (raw passthrough) ---------------------------
    async def predict_raw(self, msg: SeldonMessage) -> SeldonMessage:
        return await self._call("/predict", "Predict", msg)

    async def transform_input_raw(self, msg: SeldonMessage) -> SeldonMessage:
        return await self._call("/transform-input", "TransformInput", msg)

    async def transform_output_raw(self, msg: SeldonMessage) -> SeldonMessage:
        return await self._call("/transform-output", "TransformOutput", msg)

    async def route_raw(self, msg: SeldonMessage) -> SeldonMessage:
        return await self._call("/route", "Route", msg)

    async def aggregate_raw(self, msgs: Sequence[SeldonMessage]) -> SeldonMessage:
        lst = SeldonMessageList(messages=list(msgs))
        return await self._call("/aggregate", "Aggregate", lst)

    async def send_feedback_raw(self, feedback: Feedback) -> SeldonMessage:
        return await self._call("/send-feedback", "SendFeedback", feedback)
