"""SLO-aware weighted-fair admission scheduling for the continuous batcher.

Replaces the FIFO ``_pending`` deque (runtime/batcher.py) for multi-tenant
serving (ROADMAP item 5): requests carry a TENANT identity and an SLO
CLASS — ``interactive`` (latency-sensitive: chat, agents) or ``batch``
(throughput: evals, backfills) — and admission order is decided by stride
scheduling (Waldspurger & Weihl, OSDI '94; the deterministic form of
weighted fair queueing) instead of arrival order:

- **classes share the slots by weight.** Each class keeps a virtual time
  that advances by ``1/weight`` per admission; the nonempty class with the
  smallest virtual time admits next. Interactive's default 4:1 weight
  means a batch-tenant flood cannot queue an interactive request behind
  the whole backlog (the SLO-isolation bar in bench phase L) — while
  batch still admits every few picks, so neither class can starve: both
  properties fall out of the same stride invariant (lag bounded by one
  admission).
- **tenants share a class the same way.** Within a class, tenants run the
  identical stride scheme under per-tenant weights — one tenant's burst
  cannot crowd out its classmates.
- **deadline-aware within a tenant.** A request carrying a deadline
  (REST ``Seldon-Deadline-Ms`` / the gRPC deadline) orders by earliest
  deadline first inside its tenant queue; deadline-less requests keep
  arrival order behind a deadline only when theirs expires later (None
  sorts last). Deadlines also gate PREEMPTION, decided by the batcher: an
  interactive admission finding every slot held may push a STAGED
  batch-class job (local chunked prefill or a staged remote admission)
  back into this queue — never an ACTIVE slot; a preempted request keeps
  its original sequence number (it re-enters where it left) and is
  preempted at most once (the ``preempted`` flag), which is what makes
  the scheme livelock-free under a sustained interactive flood.
- **per-tenant quotas shed early.** ``tenant_quota`` (global default) /
  ``tenant_quotas[tenant]`` bound a tenant's QUEUED requests; a push over
  quota is refused and the batcher sheds it with 503 + the live
  backlog-derived Retry-After (runtime/resilience.py machinery) — one
  tenant's retry storm cannot occupy the whole admission queue. Sheds,
  admissions and generated tokens are tallied per (tenant, class) and
  flow llm_stats -> sync_llm -> ``seldon_tenant_*_total{tenant,slo_class}``.

Concurrency: every public method takes ``self._lock``. Pushes arrive from
the batcher's event loop (submit coroutines), pops/commits from the same
loop's admission turns, but ``__len__``/``depths``/``counters`` are read
from transport threads at /metrics scrape and by the scaling snapshot —
racelint models the class (tests/test_racelint.py fixture pair) and
tests/test_schedules.py proves an unlocked tally reconstruction loses
updates under a found schedule while this class survives exploration.
"""

from __future__ import annotations

import dataclasses
import heapq
import math
import threading
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["PendingRequest", "WeightedFairScheduler", "normalize_slo_class",
           "INTERACTIVE", "BATCH", "SLO_CLASSES", "DEFAULT_CLASS_WEIGHTS"]

INTERACTIVE = "interactive"
BATCH = "batch"
SLO_CLASSES = (INTERACTIVE, BATCH)

# cardinality bound on per-tenant tracking (tallies + metrics series):
# the tenant header is client-controlled, so past this many distinct
# (tenant, class) tallies, unseen tenants fold into one shared bucket
# (WeightedFairScheduler._resolve_tenant)
MAX_TENANT_SERIES = 512
OVERFLOW_TENANT = "~other"

# interactive admits 4 slots for every 1 batch slot when both queues are
# nonempty — latency isolation with guaranteed batch progress (bench
# phase L pins both sides of that trade)
DEFAULT_CLASS_WEIGHTS = {INTERACTIVE: 4.0, BATCH: 1.0}


def normalize_slo_class(value) -> str:
    """Canonical SLO class; raises ValueError on anything else so a typo
    in a header/config fails loudly (400 at the transport, load() error
    for server config) instead of silently landing in a default queue."""
    v = str(value or INTERACTIVE).strip().lower()
    if v in (INTERACTIVE, "latency"):
        return INTERACTIVE
    if v in (BATCH, "throughput", "bulk"):
        return BATCH
    raise ValueError(
        f"unknown SLO class {value!r}: expected one of {SLO_CLASSES}")


@dataclasses.dataclass
class PendingRequest:
    """One queued admission — the typed replacement for the positional
    8-tuple the batcher used to carry (the bare-tuple unpacks in the
    admit/shed paths were a standing foot-gun; ISSUE 15 satellite).
    ``seq`` is assigned at first push and survives requeue, so a
    preempted request re-enters its tenant queue at its original
    position; ``deadline_t`` is on the batcher's perf_counter clock."""

    ids: List[int]
    max_new: int
    fut: Any
    on_token: Optional[Any] = None
    info: Optional[dict] = None
    seed: Optional[int] = None
    t_arrival: Optional[float] = None
    trace: Optional[Any] = None
    tenant: str = ""
    slo_class: str = INTERACTIVE
    deadline_t: Optional[float] = None
    adapter_id: int = 0
    seq: int = 0
    preempted: bool = False
    # Fleet recovery (docs/resilience.md "Fleet fault tolerance"): how many
    # tokens of this generation were already delivered on a replica that
    # died. ``ids`` then carries prompt+generated-prefix and the first
    # sampled token must continue the ORIGINAL request's rng chain — the
    # batcher fast-forwards the per-request key by this many splits and
    # draws it exactly as the device sampler would have (_sample_first).
    resume_tokens: int = 0

    def _order_key(self) -> Tuple[float, int]:
        # EDF within a tenant queue; deadline-less requests keep arrival
        # order after every deadline-carrying one
        dk = self.deadline_t if self.deadline_t is not None else math.inf
        return (dk, self.seq)


class _TenantTally:
    __slots__ = ("admitted", "shed", "tokens", "queued", "preempted")

    def __init__(self):
        self.admitted = 0
        self.shed = 0
        self.tokens = 0
        self.queued = 0
        self.preempted = 0


class WeightedFairScheduler:
    """See module docstring. ``class_weights`` / ``tenant_weights``
    override the defaults (missing tenants weigh 1); ``tenant_quota`` is
    the global per-tenant queued-request bound (0 = unbounded) with
    ``tenant_quotas`` per-tenant overrides."""

    def __init__(self, class_weights: Optional[Dict[str, float]] = None,
                 tenant_weights: Optional[Dict[str, float]] = None,
                 tenant_quota: int = 0,
                 tenant_quotas: Optional[Dict[str, int]] = None):
        self._lock = threading.Lock()
        weights = dict(DEFAULT_CLASS_WEIGHTS)
        for cls, w in (class_weights or {}).items():
            cls = normalize_slo_class(cls)
            if float(w) <= 0:
                raise ValueError(f"class weight for {cls!r} must be > 0")
            weights[cls] = float(w)
        self._class_weights = weights
        self._tenant_weights = {str(t): float(w)
                                for t, w in (tenant_weights or {}).items()}
        self._tenant_quota = int(tenant_quota)
        self._tenant_quotas = {str(t): int(q)
                               for t, q in (tenant_quotas or {}).items()}
        # (cls, tenant) -> heap of (order_key, req); heaps hold only live
        # entries (commit removes by identity, not lazily)
        self._queues: Dict[Tuple[str, str], List[Tuple[Tuple[float, int],
                                                       int, PendingRequest]]] = {}
        self._class_vt: Dict[str, float] = {c: 0.0 for c in SLO_CLASSES}
        self._tenant_vt: Dict[Tuple[str, str], float] = {}
        # the virtual-time floor: a class/tenant going idle must not bank
        # credit — on re-arrival its vt catches up to the last pick's
        self._vt_floor = 0.0
        self._tenant_vt_floor: Dict[str, float] = {c: 0.0 for c in SLO_CLASSES}
        self._tenants: Dict[Tuple[str, str], _TenantTally] = {}
        self._seq = 0
        self._size = 0

    # ------------------------------------------------------------------
    def _resolve_tenant(self, tenant: str) -> str:
        """Bound the tenant cardinality the scheduler TRACKS: the tenant
        header is client-controlled, and without a cap every unique value
        would permanently allocate a tally and one more
        seldon_tenant_*_total{tenant=...} Prometheus series per scrape.
        Known tenants keep their own tallies; once MAX_TENANT_SERIES
        distinct (tenant, class) tallies exist, UNSEEN tenants fold into
        the shared OVERFLOW_TENANT bucket (quota then applies to the
        bucket in aggregate — deliberately conservative under a
        cardinality flood). Configure real tenants in tenant_weights /
        tenant_quotas and size the cap accordingly."""
        if ((tenant, INTERACTIVE) in self._tenants
                or (tenant, BATCH) in self._tenants):
            return tenant
        if len(self._tenants) >= MAX_TENANT_SERIES:
            return OVERFLOW_TENANT
        return tenant

    def _tally(self, tenant: str, cls: str) -> _TenantTally:
        tenant = self._resolve_tenant(tenant)
        t = self._tenants.get((tenant, cls))
        if t is None:
            t = self._tenants[(tenant, cls)] = _TenantTally()
        return t

    def _quota_of(self, tenant: str) -> int:
        return self._tenant_quotas.get(tenant, self._tenant_quota)

    # ------------------------------------------------------------------
    def push(self, req: PendingRequest, requeue: bool = False) -> bool:
        """Queue one request. Returns False — and counts the shed —
        when the tenant is over its queued-request quota (the batcher
        turns that into 503 + Retry-After). ``requeue=True`` is the
        preemption return path: quota is skipped (the request was
        already admitted once) and the original seq keeps its position."""
        with self._lock:
            cls = req.slo_class
            tenant = req.tenant
            tally = self._tally(tenant, cls)
            if not requeue:
                quota = self._quota_of(tenant)
                tracked = self._resolve_tenant(tenant)
                queued = sum(
                    t.queued for (tn, _), t in self._tenants.items()
                    if tn == tracked)
                if quota > 0 and queued >= quota:
                    tally.shed += 1
                    return False
                self._seq += 1
                req.seq = self._seq
            else:
                tally.preempted += 1
                req.preempted = True
            # idle catch-up BEFORE the push: a class/tenant that sat empty
            # must not bank virtual-time credit it would then spend
            # monopolizing admissions
            if self._class_empty(cls):
                self._class_vt[cls] = max(self._class_vt[cls], self._vt_floor)
            key = (cls, tenant)
            q = self._queues.get(key)
            if q is None:
                q = self._queues[key] = []
            if not q:
                self._tenant_vt[key] = max(
                    self._tenant_vt.get(key, 0.0),
                    self._tenant_vt_floor[cls])
            heapq.heappush(q, (req._order_key(), req.seq, req))
            tally.queued += 1
            self._size += 1
            return True

    def _class_empty(self, cls: str) -> bool:
        return not any(q for (c, _), q in self._queues.items() if c == cls)

    # ------------------------------------------------------------------
    def next_request(self) -> Optional[PendingRequest]:
        """Peek the next admission per policy WITHOUT removing it — the
        batcher's peek-try-commit idiom (a failed admit keeps the
        request queued for the next loop turn)."""
        with self._lock:
            pick = self._pick_locked()
            return None if pick is None else pick[1][0][2]

    def _pick_locked(self):
        # class by min virtual time (nonempty only; tie -> interactive)
        best_cls = None
        for cls in SLO_CLASSES:
            if self._class_empty(cls):
                continue
            if best_cls is None or self._class_vt[cls] < self._class_vt[best_cls]:
                best_cls = cls
        if best_cls is None:
            return None
        # tenant within the class, same rule (tie -> lowest head seq so
        # the order is deterministic and arrival-respecting)
        best_key, best_q = None, None
        for key, q in self._queues.items():
            if key[0] != best_cls or not q:
                continue
            if best_key is None:
                best_key, best_q = key, q
                continue
            vt_a = self._tenant_vt.get(key, 0.0)
            vt_b = self._tenant_vt.get(best_key, 0.0)
            if vt_a < vt_b or (vt_a == vt_b and q[0][1] < best_q[0][1]):
                best_key, best_q = key, q
        return best_key, best_q

    def commit(self, req: PendingRequest) -> None:
        """Remove ``req`` (admitted into a slot / staged) and advance the
        virtual clocks — the other half of the peek-try-commit pair.
        Removal is by identity: a push that slipped in between the peek
        and this commit may have changed the head."""
        with self._lock:
            key = (req.slo_class, req.tenant)
            q = self._queues.get(key)
            if q is None:
                return
            # read BEFORE _remove_from: emptying the queue prunes the vt
            # entry, and the floors below must still see the advance
            old_vt = self._tenant_vt.get(key, 0.0)
            if not self._remove_from(key, q, req):
                return
            self._size -= 1
            tally = self._tally(req.tenant, req.slo_class)
            tally.queued = max(tally.queued - 1, 0)
            if not req.preempted:
                # a preempted request already counted at its FIRST
                # admission — admitted tallies unique requests, while the
                # virtual clocks below advance on every admission event
                # (the re-admission consumes class bandwidth again)
                tally.admitted += 1
            cls = req.slo_class
            self._class_vt[cls] += 1.0 / self._class_weights[cls]
            w = self._tenant_weights.get(req.tenant, 1.0)
            new_vt = old_vt + 1.0 / w
            self._vt_floor = max(self._vt_floor, self._class_vt[cls])
            self._tenant_vt_floor[cls] = max(self._tenant_vt_floor[cls],
                                             new_vt)
            if key in self._queues:  # still queued: keep the live vt
                self._tenant_vt[key] = new_vt

    def _remove_from(self, key, q, req) -> bool:
        """Identity-remove ``req`` from its tenant heap. The committed
        request is almost always the head next_request() just peeked, so
        the common case is one O(log n) heappop — the O(n) scan+heapify
        only runs when a racing push changed the head. Emptied heaps
        prune their map entries (client-controlled tenant names must not
        grow the maps unboundedly); the pruned virtual time is
        re-created AT THE FLOOR on re-arrival, which is exactly push()'s
        no-banked-credit catch-up."""
        if q and q[0][2] is req:
            heapq.heappop(q)
        else:
            for i, (_, _, r) in enumerate(q):
                if r is req:
                    q.pop(i)
                    heapq.heapify(q)
                    break
            else:
                return False
        if not q:
            del self._queues[key]
            self._tenant_vt.pop(key, None)
        return True

    def remove(self, req: PendingRequest) -> bool:
        """Drop a queued request without admitting it (quota-less shed
        paths; the crash drain uses drain_all). Counts the shed."""
        with self._lock:
            key = (req.slo_class, req.tenant)
            q = self._queues.get(key)
            if not q:
                return False
            if not self._remove_from(key, q, req):
                return False
            self._size -= 1
            tally = self._tally(req.tenant, req.slo_class)
            tally.queued = max(tally.queued - 1, 0)
            tally.shed += 1
            return True

    def drain_all(self) -> List[PendingRequest]:
        """Remove and return every queued request (batcher crash path:
        each one's future is failed)."""
        with self._lock:
            out: List[PendingRequest] = []
            for q in self._queues.values():
                out.extend(r for _, _, r in q)
            self._queues.clear()
            self._tenant_vt.clear()
            for tally in self._tenants.values():
                tally.queued = 0
            self._size = 0
            out.sort(key=lambda r: r.seq)
            return out

    # ------------------------------------------------------------------
    # accounting surface (batcher post-admission paths + metrics)
    # ------------------------------------------------------------------
    def count_shed(self, tenant: str, slo_class: str) -> None:
        """A post-admission shed (page exhaustion victim, staged-job
        shed) attributed to its tenant."""
        with self._lock:
            self._tally(tenant, slo_class).shed += 1

    def count_tokens(self, tenant: str, slo_class: str, n: int) -> None:
        with self._lock:
            self._tally(tenant, slo_class).tokens += int(n)

    # ------------------------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return self._size

    def depths(self) -> Dict[str, int]:
        """Queued requests per SLO class (the scaling snapshot's
        ``queue_by_class`` block)."""
        with self._lock:
            out = {c: 0 for c in SLO_CLASSES}
            for (cls, _), q in self._queues.items():
                out[cls] += len(q)
            return out

    def counters(self) -> List[Dict[str, Any]]:
        """Per-(tenant, class) lifetime tallies for llm_stats ->
        sync_llm -> seldon_tenant_*_total{tenant,slo_class}."""
        with self._lock:
            return [
                {"tenant": tenant, "slo_class": cls,
                 "admitted": t.admitted, "shed": t.shed,
                 "tokens": t.tokens, "queued": t.queued,
                 "preempted": t.preempted}
                for (tenant, cls), t in sorted(self._tenants.items())
            ]
