"""Component state persistence.

Capability of the reference's Redis pickle persistence for stateful routers
(`python/seldon_core/persistence.py:21-85`: periodic pickle of the live user
object under key ``persistence_{DEPLOYMENT}_{PREDICTOR}_{UNIT}``, restore on
boot). Backend is pluggable: file-backed by default (works everywhere), Redis
when a server + client library are available.
"""

from __future__ import annotations

import logging
import os
import pickle
import threading
import time
from typing import Any, Optional

logger = logging.getLogger(__name__)

DEFAULT_PERIOD_S = 60.0  # reference default (`persistence.py:68-85`)


def state_key(env: Optional[dict] = None) -> str:
    env = env if env is not None else dict(os.environ)
    return "persistence_{}_{}_{}".format(
        env.get("DEPLOYMENT_NAME", "dep"),
        env.get("PREDICTOR_ID", "pred"),
        env.get("PREDICTIVE_UNIT_ID", "unit"),
    )


class StateStore:
    def save(self, key: str, obj: Any) -> None:
        raise NotImplementedError

    def restore(self, key: str) -> Optional[Any]:
        raise NotImplementedError

    def list(self, prefix: str) -> list:
        """Keys starting with ``prefix`` (replica discovery)."""
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def save_if_absent(self, key: str, obj: Any) -> bool:
        """Atomically create; False if the key already exists (one-shot
        claims, e.g. legacy-state adoption)."""
        raise NotImplementedError


class FileStateStore(StateStore):
    def __init__(self, root: Optional[str] = None):
        self.root = root or os.environ.get("PERSISTENCE_DIR", "/tmp/seldon-tpu-state")
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, key + ".pkl")

    def save(self, key: str, obj: Any) -> None:
        # tmp name unique per process: on a shared volume multiple replicas
        # save the same key concurrently — a shared tmp file would interleave
        # writes and os.replace would install a torn pickle
        tmp = f"{self._path(key)}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            pickle.dump(obj, f)
        os.replace(tmp, self._path(key))

    def restore(self, key: str) -> Optional[Any]:
        try:
            with open(self._path(key), "rb") as f:
                return pickle.load(f)
        except FileNotFoundError:
            # a concurrent GC (replica-key expiry) may delete between list
            # and open; absent is absent
            return None

    def list(self, prefix: str) -> list:
        return sorted(
            fn[: -len(".pkl")]
            for fn in os.listdir(self.root)
            if fn.endswith(".pkl") and fn.startswith(prefix)
        )

    def delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    def save_if_absent(self, key: str, obj: Any) -> bool:
        # write the payload fully in a RANDOM tmp file, then link into place:
        # the key only becomes visible complete, a crash mid-dump can't leave
        # a torn claim, and the random name can't collide across replicas
        # (pid-keyed tmp names do collide — every container's main process
        # tends to be pid 1)
        import tempfile

        fd, tmp = tempfile.mkstemp(prefix=".claim-", dir=self.root)
        try:
            os.fchmod(fd, 0o644)  # mkstemp's 0600 would follow the hard link
            with os.fdopen(fd, "wb") as f:
                pickle.dump(obj, f)
            try:
                os.link(tmp, self._path(key))
                return True
            except FileExistsError:
                return False
        finally:
            os.unlink(tmp)


class RedisStateStore(StateStore):
    def __init__(self, host: Optional[str] = None, port: int = 6379):
        try:
            import redis
        except ImportError as e:
            raise RuntimeError("RedisStateStore requires the redis package") from e
        self._client = redis.StrictRedis(
            host=host or os.environ.get("REDIS_SERVICE_HOST", "localhost"), port=port
        )

    def save(self, key: str, obj: Any) -> None:
        self._client.set(key, pickle.dumps(obj))

    def restore(self, key: str) -> Optional[Any]:
        raw = self._client.get(key)
        return pickle.loads(raw) if raw else None

    def list(self, prefix: str) -> list:
        return sorted(
            k.decode() if isinstance(k, bytes) else k
            for k in self._client.scan_iter(match=prefix + "*")
        )

    def delete(self, key: str) -> None:
        self._client.delete(key)

    def save_if_absent(self, key: str, obj: Any) -> bool:
        return bool(self._client.set(key, pickle.dumps(obj), nx=True))


def make_store() -> StateStore:
    if os.environ.get("REDIS_SERVICE_HOST"):
        try:
            return RedisStateStore()
        except RuntimeError:
            logger.warning("REDIS_SERVICE_HOST set but redis client unavailable; using file store")
    return FileStateStore()


def restore_component(component_class, key: Optional[str] = None, store: Optional[StateStore] = None):
    """Restore a live component of the given class, or None. Class mismatch
    discards stale state (same guard as `persistence.py:34-41`)."""
    store = store or make_store()
    key = key or state_key()
    obj = store.restore(key)
    if obj is None:
        return None
    if type(obj).__name__ != component_class.__name__:
        logger.warning("persisted state is a %s, expected %s; ignoring", type(obj).__name__, component_class.__name__)
        return None
    return obj


class PersistenceThread(threading.Thread):
    """Periodically snapshots the live component (daemon thread)."""

    def __init__(self, component: Any, key: Optional[str] = None, store: Optional[StateStore] = None,
                 period_s: float = DEFAULT_PERIOD_S):
        super().__init__(daemon=True, name="seldon-persistence")
        self.component = component
        self.key = key or state_key()
        self.store = store or make_store()
        self.period_s = period_s
        self._halt = threading.Event()

    def run(self) -> None:
        while not self._halt.wait(self.period_s):
            self.snapshot()

    def snapshot(self) -> None:
        try:
            self.store.save(self.key, self.component)
        except Exception:
            logger.exception("persistence snapshot failed")

    def stop(self) -> None:
        self._halt.set()
        self.snapshot()


def replica_id(env: Optional[dict] = None) -> str:
    """Identity for this serving replica. REPLICA_ID (set it to the pod
    name/ordinal in k8s) gives a STABLE identity: a restarted replica
    resumes its own counter. The default is hostname-pid — collision-free
    for co-hosted replicas; a restart starts a fresh counter while the old
    key keeps contributing as a peer, so no feedback is lost either way."""
    env = env if env is not None else dict(os.environ)
    explicit = env.get("REPLICA_ID")
    if explicit:
        return explicit
    return f"{env.get('HOSTNAME', 'host')}-pid{os.getpid()}"


class ReplicaSync(threading.Thread):
    """Multi-replica state sharing for stateful routers (SURVEY.md §7 hard
    part #4: bandit feedback under replicated data-parallel serving).

    G-counter protocol — no CAS, no double counting: each replica OWNS the
    key ``{key}:replica:{id}`` and periodically publishes only its local
    statistics there; it then reads every *other* replica's snapshot and
    installs the sum as its peer contribution
    (`_BanditRouter.apply_peer_stats`). Decisions see local + peers, so all
    replicas converge on the global posterior between sync periods, any
    replica can crash without corrupting shared state, and a restarted
    replica resumes its own counter from its own key.

    Works over any StateStore with list(): a shared volume (FileStateStore)
    or Redis — the same backends the reference's single-writer pickle used.
    """

    # dead-replica keys older than this are garbage-collected by any live
    # replica's sync (REPLICA_ID users republish continuously, so only truly
    # dead counters expire; their history has already been observed and will
    # drift out of relevance as live counts grow)
    DEFAULT_EXPIRE_S = 7 * 24 * 3600.0

    def __init__(
        self,
        component: Any,
        key: Optional[str] = None,
        store: Optional[StateStore] = None,
        rid: Optional[str] = None,
        period_s: float = 5.0,
        expire_after_s: Optional[float] = DEFAULT_EXPIRE_S,
    ):
        super().__init__(daemon=True, name="seldon-replica-sync")
        for method in ("stats_snapshot", "apply_peer_stats", "load_stats_snapshot"):
            if not hasattr(component, method):
                raise TypeError(
                    f"{type(component).__name__} does not expose {method} "
                    "(required for replica sync)"
                )
        self.component = component
        self.key = key or state_key()
        self.store = store or make_store()
        self.rid = rid or replica_id()
        self.period_s = period_s
        self.expire_after_s = expire_after_s
        self._halt = threading.Event()

    @property
    def own_key(self) -> str:
        return f"{self.key}:replica:{self.rid}"

    def sync(self) -> None:
        try:
            snap = self.component.stats_snapshot()
            snap["ts"] = time.time()
            self.store.save(self.own_key, snap)
            peers = []
            now = time.time()
            for k in self.store.list(f"{self.key}:replica:"):
                if k == self.own_key:
                    continue
                peer = self.store.restore(k)
                if peer is None:
                    continue
                age = now - float(peer.get("ts", now))
                if self.expire_after_s is not None and age > self.expire_after_s:
                    logger.info("expiring dead replica key %s (age %.0fs)", k, age)
                    self.store.delete(k)
                    continue
                peers.append(peer)
            self.component.apply_peer_stats(peers)
        except Exception:
            logger.exception("replica sync failed (will retry)")

    def restore_own(self) -> bool:
        """On boot: resume this replica's own counter if present and
        shape-compatible (the component validates — a redeploy with a
        different branch count rejects the stale snapshot)."""
        snap = self.store.restore(self.own_key)
        if snap is None:
            return False
        return bool(self.component.load_stats_snapshot(snap))

    def run(self) -> None:
        while not self._halt.wait(self.period_s):
            self.sync()
        self.sync()  # final publish so peers see the last counts

    def stop(self) -> None:
        self._halt.set()
        self.join(timeout=self.period_s + 1)
