"""Per-slot flight recorder: request-scoped timelines through the serving
hot path.

The aggregate histograms (TTFT, inter-token gap, handoff wall — PR 9) say
*that* tail latency exists; they cannot say why *this* request saw a 200 ms
inter-token gap. The flight recorder answers that: every slot carries a
fixed-size ring of timestamped lifecycle events — admission (with queue
wait), each prefill chunk, disaggregated-handoff stages, every drained
decode step with its token count and speculative accept count, page-grow
stalls, sheds, EOS — written by the batcher at points that ALREADY touch
host state, and materialized into one span tree per request at completion
(fed to the Tracer/OTLP exporter, surfaced at ``/debug/timeline``).

Concurrency discipline (racelint-modeled; proven under deterministic
interleaving in tests/test_schedules.py):

- The per-slot segments and their event rings are SINGLE-WRITER: only the
  batcher loop's serialized offload context (the same context that owns all
  slot bookkeeping) calls ``begin``/``record``/``extend``/``complete``.
  No lock is acquired on the decode dispatch/drain path — the recorder adds
  appends, never synchronization, which is what keeps enabled-tracing
  throughput within the bench guard (benchmarks/llm_batch_bench.py
  ``--tracing``).
- Prefill-slice worker threads never touch a slot ring. They stamp their
  events into the ``Handoff`` record BEFORE publishing it through the
  TransferQueue (ownership transfers under the queue's lock, exactly-once),
  and the batcher copies them in at consume time via ``extend``.
- Only the completed-timeline ring and the scaling aggregates cross
  threads (``/debug/timeline`` + ``/metrics`` readers); they are guarded by
  ``self._lock``, acquired once per REQUEST at completion — never per
  decode step.

Zero work when disabled: the batcher holds ``_flight = None`` unless the
tracer is enabled, every hook is a None check, and no compiled program
changes either way (hlolint contracts are identical with TRACING=0/1).
"""

from __future__ import annotations

import random
import secrets
import threading
import time
from collections import deque
from typing import Any, List, Optional

from seldon_core_tpu.tracing import Span, TraceContext, Tracer, now as wall_now

# event kinds (timeline "kind" field / span names); slot reservation and
# queue wait are segment FIELDS (begin()), not ring events
EV_PREFILL_CHUNK = "prefill_chunk"  # one chunked-prefill dispatch
EV_PREFILL = "prefill"              # one-shot dense prefill
EV_PREFIX_HIT = "prefix_hit"        # radix prefix-cache hit: tokens served
#                                     from shared pages (fields: tokens
#                                     matched, blocks = block-table entries
#                                     written instead of prefilled) —
#                                     materializes as the llm.prefix_hit
#                                     span child with the matched-block count
EV_FIRST_TOKEN = "first_token"      # commit: prefill-sampled token surfaced
EV_STEP = "step"                    # drained decode step credited to a slot
EV_PAGE_GROW = "page_grow"          # mid-decode page allocation (stall risk)
EV_HANDOFF_STAGED = "handoff_staged"        # remote job staged (disagg)
EV_HANDOFF_COMPUTE = "handoff_compute"      # prefill-slice forward (worker)
EV_HANDOFF_TRANSFER = "handoff_transfer"    # device-to-device KV move
EV_HANDOFF_IMPORT = "handoff_import"        # decode-side page import
EV_SHED = "shed"                    # request shed (503 + Retry-After)
EV_RESUME = "resume"                # fleet recovery: re-admitted with N
                                    # already-delivered tokens (the rng
                                    # chain fast-forwarded past them)

DEFAULT_RING = 512   # events per in-flight request (~max_new steps + admission)
DEFAULT_KEEP = 64    # completed timelines retained for /debug/timeline


class _Segment:
    """One request's in-flight recording: its trace identity and the event
    ring. ``total`` counts every append so ring overflow is observable
    (``events_dropped`` = total - len(ring)). The latency/token signals
    (``t_first``, ``worst_gap``, ``tokens``) accumulate HERE at record
    time, not from the ring at materialization: a generation longer than
    the ring evicts its early events, and deriving TTFT from the ring
    would silently disable TTFT tail-sampling (and undercount tokens) for
    exactly the long slow requests the recorder exists to explain."""

    __slots__ = ("trace", "t_submit", "t_begin", "prompt_tokens", "ring",
                 "total", "t_first", "last_surface", "worst_gap", "tokens",
                 "tags")

    def __init__(self, trace: TraceContext, t_submit: Optional[float],
                 t_begin: float, prompt_tokens: int, ring_size: int,
                 tags: Optional[dict] = None):
        # request identity tags (tenant / slo_class / adapter_id for
        # multi-tenant serving): merged into the materialized timeline
        # and the root span's tags
        self.tags = tags
        self.trace = trace
        self.t_submit = t_submit if t_submit is not None else t_begin
        self.t_begin = t_begin
        self.prompt_tokens = prompt_tokens
        self.ring: Any = deque(maxlen=ring_size)
        self.total = 0
        self.t_first: Optional[float] = None
        self.last_surface: Optional[float] = None
        self.worst_gap: Optional[float] = None
        self.tokens = 0


class FlightRecorder:
    """See module docstring. ``clock`` must match the batcher's timestamp
    source (``time.perf_counter`` — submit()'s ``t_arrival`` and the
    in-flight records' ``t_dispatch`` are drawn from it); materialization
    converts to wall time through the anchor pair captured at init."""

    def __init__(self, n_slots: int, ring_size: int = DEFAULT_RING,
                 keep: int = DEFAULT_KEEP,
                 tail_ttft_s: Optional[float] = None,
                 tail_gap_s: Optional[float] = None,
                 clock=time.perf_counter):
        self.n_slots = int(n_slots)
        self.ring_size = int(ring_size)
        self.tail_ttft_s = tail_ttft_s
        self.tail_gap_s = tail_gap_s
        self._clock = clock
        # perf-counter -> wall anchor (tracing.now() is the wall source so
        # exported spans and Span() timestamps share one clock discipline).
        # REFRESHED at every materialization (_reanchor) rather than frozen
        # at init: a deployment that fixes NTP late and calls
        # tracing.anchor() must see its correction in flight-recorder
        # timestamps too, or node spans and request trees in the same
        # trace would disagree by the whole correction.
        self._wall0 = wall_now()
        self._perf0 = clock()
        self._segs: List[Optional[_Segment]] = [None] * self.n_slots
        # cross-thread surface: completed timelines + scaling aggregates,
        # written once per request under the lock, read by /debug/timeline
        # and /metrics scrape threads
        self._lock = threading.Lock()
        self._completed: Any = deque(maxlen=int(keep))
        self.completed_total = 0
        self.retained = {"head": 0, "tail": 0, "drop": 0}
        self.events_dropped_total = 0
        self._ttft: Any = deque(maxlen=256)
        self._queue_wait: Any = deque(maxlen=256)
        self._worst_gap: Any = deque(maxlen=256)
        # Span-id source for materialization: a PRNG seeded ONCE from the
        # system entropy pool instead of secrets.token_hex per id — a
        # request tree is ~40 ids and each token_hex is a urandom syscall,
        # which alone busts the <=2% tracing-overhead budget at toy decode
        # step times. Ids need uniqueness, not crypto strength; used only
        # from the single-writer materialization context.
        self._id_rng = random.Random(secrets.randbits(64))

    def _span_id(self) -> str:
        return f"{self._id_rng.getrandbits(64):016x}"

    def _trace_id(self) -> str:
        return f"{self._id_rng.getrandbits(128):032x}"

    # -- single-writer side (batcher loop context only) -----------------
    def begin(self, slot: int, trace: Optional[TraceContext],
              t_submit: Optional[float], prompt_tokens: int,
              tags: Optional[dict] = None) -> None:
        """Start recording a request at the moment its slot is chosen.
        ``trace`` may be None (an untraced submit while the recorder runs
        for others) — the segment still records, rooted at a fresh trace
        id, so /debug/timeline sees every request. ``tags`` (optional
        request identity: tenant / slo_class / adapter_id) ride the
        timeline dict and the root span."""
        if trace is None:
            trace = TraceContext(trace_id=self._trace_id(),
                                 sampled=True, ingress="internal")
        self._segs[slot] = _Segment(trace, t_submit, self._clock(),
                                    prompt_tokens, self.ring_size,
                                    tags=tags)

    def record(self, slot: int, kind: str, **fields: Any) -> None:
        seg = self._segs[slot]
        if seg is None:
            return
        seg.total += 1
        t = self._clock()
        if kind == EV_FIRST_TOKEN or kind == EV_STEP:
            seg.tokens += int(fields.get("tokens", 0))
            if seg.t_first is None and kind == EV_FIRST_TOKEN:
                seg.t_first = t
            if seg.last_surface is not None:
                gap = t - seg.last_surface
                if seg.worst_gap is None or gap > seg.worst_gap:
                    seg.worst_gap = gap
            seg.last_surface = t
        seg.ring.append((t, kind, fields))

    def extend(self, slot: int, events) -> None:
        """Copy worker-stamped events (Handoff.events: (t, kind, fields)
        tuples on this process's perf_counter clock) into the slot ring —
        the batcher-side half of the single-writer handoff."""
        seg = self._segs[slot]
        if seg is None:
            return
        for t, kind, fields in events:
            seg.total += 1
            seg.ring.append((t, kind, fields))

    def complete(self, slot: int, status: str, tokens: int,
                 tracer: Optional[Tracer] = None) -> Optional[dict]:
        """Materialize the slot's segment into a timeline dict + span tree:
        decide retention (head flag, else tail thresholds), feed retained
        trees to the tracer, publish the timeline for /debug/timeline, and
        clear the segment. The ONLY lock acquisition in the recorder's
        write path — once per request."""
        seg = self._segs[slot]
        if seg is None:
            return None
        self._segs[slot] = None
        self._reanchor()
        t_end = self._clock()
        events = list(seg.ring)
        timeline = self._materialize(seg, events, slot, status, tokens, t_end)
        mode = timeline["sampling"]
        if tracer is not None and tracer.enabled and mode != "drop":
            tracer.record_spans(self._spans(seg, events, timeline, t_end))
            tracer.count_retained(mode)
        dropped = seg.total - len(events)
        with self._lock:
            self._completed.append(timeline)
            self.completed_total += 1
            self.retained[mode] = self.retained.get(mode, 0) + 1
            self.events_dropped_total += dropped
            if timeline["ttft_s"] is not None:
                self._ttft.append(timeline["ttft_s"])
            self._queue_wait.append(timeline["queue_wait_s"])
            if timeline["worst_gap_s"] is not None:
                self._worst_gap.append(timeline["worst_gap_s"])
        return timeline

    # -- materialization -------------------------------------------------
    def _reanchor(self) -> None:
        """Refresh the perf->wall mapping through tracing.now()'s CURRENT
        anchor (single-writer context; called once per materialization so
        every timestamp of one request tree shares one mapping)."""
        self._wall0 = wall_now()
        self._perf0 = self._clock()

    def _wall(self, t: float) -> float:
        return self._wall0 + (t - self._perf0)

    def _materialize(self, seg: _Segment, events, slot: int, status: str,
                     tokens: int, t_end: float) -> dict:
        # latency/token signals come from the SEGMENT accumulators (record
        # time), never the ring: eviction must not erase TTFT or tokens
        ttft = (seg.t_first - seg.t_submit) if seg.t_first is not None else None
        worst_gap = seg.worst_gap
        step_tokens = seg.tokens
        if seg.trace.sampled:
            mode = "head"
        elif (self.tail_ttft_s is not None and ttft is not None
                and ttft > self.tail_ttft_s) or \
             (self.tail_gap_s is not None and worst_gap is not None
                and worst_gap > self.tail_gap_s):
            mode = "tail"
        else:
            mode = "drop"
        return {
            "trace_id": seg.trace.trace_id,
            "ingress": seg.trace.ingress,
            "slot": slot,
            "status": status,
            **({"request_tags": dict(seg.tags)} if seg.tags else {}),
            "sampling": mode,
            "t_submit_wall": self._wall(seg.t_submit),
            "queue_wait_s": seg.t_begin - seg.t_submit,
            "ttft_s": ttft,
            "worst_gap_s": worst_gap,
            "total_s": t_end - seg.t_submit,
            "prompt_tokens": seg.prompt_tokens,
            "tokens": tokens,
            "token_events_sum": step_tokens,
            "events_dropped": seg.total - len(events),
            "events": [self._event_dict(seg, t, kind, fields)
                       for t, kind, fields in events],
        }

    @staticmethod
    def _event_dict(seg: _Segment, t: float, kind: str, fields: dict) -> dict:
        out = {"t_s": round(t - seg.t_submit, 6), "kind": kind}
        for k, v in fields.items():
            if k == "t_dispatch":
                # raw perf-counter stamps mean nothing to a client —
                # render submit-relative like t_s
                out["t_dispatch_s"] = round(float(v) - seg.t_submit, 6)
            else:
                out[k] = v
        return out

    def _spans(self, seg: _Segment, events, timeline: dict,
               t_end: float) -> List[Span]:
        """The request's span tree: one root at the transport ingress, a
        queue-wait child, one child per recorded lifecycle event (decode
        steps span dispatch -> drain). Tail-retained trees flip sampled on
        so the exporter ships them despite the head decision."""
        trace = seg.trace
        # Tail-retained trees detach from the caller's span: head sampling
        # DROPPED the in-process server/node spans (they were unsampled),
        # so parenting under trace.parent_span_id would reference a span
        # the collector never receives — a broken fragment for exactly the
        # slow requests tail sampling exists to keep. The trace id still
        # joins the caller's trace; the would-be parent rides as a tag.
        head = timeline["sampling"] == "head"
        root_tags_extra = {}
        if not head and trace.parent_span_id:
            root_tags_extra["caller_span_id"] = trace.parent_span_id
        root = Span(
            name=f"llm.request {trace.ingress}".strip(),
            trace_id=trace.trace_id, span_id=self._span_id(),
            parent_id=trace.parent_span_id if head else None,
            start=self._wall(seg.t_submit), end=self._wall(t_end),
            tags={
                "slot": timeline["slot"], "status": timeline["status"],
                "tokens": timeline["tokens"],
                "prompt_tokens": timeline["prompt_tokens"],
                "sampling": timeline["sampling"],
                "ttft_ms": round((timeline["ttft_s"] or 0.0) * 1e3, 3),
                "worst_gap_ms": round((timeline["worst_gap_s"] or 0.0) * 1e3, 3),
                "events_dropped": timeline["events_dropped"],
                **(seg.tags or {}),
                **root_tags_extra,
            })
        spans = [root]
        spans.append(Span(
            name="queue.wait", trace_id=trace.trace_id,
            span_id=self._span_id(), parent_id=root.span_id,
            start=self._wall(seg.t_submit), end=self._wall(seg.t_begin),
            tags={}))
        decode_start = None
        for t, kind, fields in events:
            wall_t = self._wall(t)
            # duration-bearing events span [t - dur, t]; instants are points
            dur = float(fields.get("dur_s", 0.0) or 0.0)
            start = wall_t - dur
            if kind == EV_STEP and "t_dispatch" in fields:
                start = self._wall(float(fields["t_dispatch"]))
            if kind == EV_FIRST_TOKEN and decode_start is None:
                decode_start = t
            tags = {k: v for k, v in fields.items()
                    if k not in ("dur_s", "t_dispatch")}
            spans.append(Span(
                name=f"llm.{kind}", trace_id=trace.trace_id,
                span_id=self._span_id(), parent_id=root.span_id,
                start=start, end=wall_t, tags=tags))
        if decode_start is not None:
            spans.append(Span(
                name="llm.decode", trace_id=trace.trace_id,
                span_id=self._span_id(), parent_id=root.span_id,
                start=self._wall(decode_start), end=self._wall(t_end),
                tags={"tokens": timeline["tokens"]}))
        for s in spans:
            s.sampled = True  # retention already decided (head or tail)
        return spans

    # -- cross-thread read side ------------------------------------------
    def timelines(self, n: int = DEFAULT_KEEP) -> List[dict]:
        """The ``n`` most recent completed request timelines, newest last
        (n <= 0 means none — reachable from the raw ?n= query param, where
        an unclamped -0/-k slice would return everything/an odd middle
        cut)."""
        n = int(n)
        if n <= 0:
            return []
        with self._lock:
            items = list(self._completed)
        return items[-n:]

    def snapshot(self) -> dict:
        """The aggregated scaling-signal snapshot (ROADMAP item 4's input):
        per-request latency signals reduced to the quantiles a controller
        steers by, plus the retention/drop tallies."""

        def stats(values) -> dict:
            if not values:
                return {"p50": None, "p95": None, "max": None}
            vs = sorted(values)
            return {
                "p50": vs[len(vs) // 2],
                "p95": vs[min(int(len(vs) * 0.95), len(vs) - 1)],
                "max": vs[-1],
            }

        with self._lock:
            return {
                "completed_total": self.completed_total,
                "retained": dict(self.retained),
                "events_dropped_total": self.events_dropped_total,
                "ttft_s": stats(list(self._ttft)),
                "queue_wait_s": stats(list(self._queue_wait)),
                "worst_gap_s": stats(list(self._worst_gap)),
            }
