"""In-process inference-graph engine.

Implements the reference orchestrator's graph semantics
(`engine/src/main/java/io/seldon/engine/predictors/PredictiveUnitBean.java:81-237`):

    per node: transformInput -> route (-1 = all children) -> children ->
              aggregate -> transformOutput
    meta: merge tags, accumulate metrics, record routing + requestPath
    feedback: deliver to node, then replay only down the routed branch

with two deliberate architecture changes:

1. **One process, zero hops.** The reference pays a network round-trip and an
   ndarray<->proto codec per node (`service/InternalPredictionService.java:
   354-443`). Here every in-process node is a direct call; only nodes with an
   explicit ``endpoint`` go over the network (runtime.remote).
2. **Whole-graph XLA fusion.** Router-free subgraphs whose components expose
   ``jax_fn()`` are composed into a single jitted function at build time, so a
   MODEL->COMBINER fan-out executes as one fused XLA program on TPU rather
   than N async futures (`PredictiveUnitBean.java:167-177`'s thread pool).

The engine also builds graph state ONCE at startup — the reference rebuilds it
per request (`service/PredictionService.java:113`), which SURVEY.md flags as a
hot-path cost to avoid.
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import secrets
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple

import numpy as np

from seldon_core_tpu.components import dispatch
from seldon_core_tpu.components.builtin import make_builtin
from seldon_core_tpu.components.component import SeldonComponent
from seldon_core_tpu.contracts.graph import (
    PredictiveUnit,
    PredictorSpec,
    UnitImplementation,
    UnitMethod,
    UnitType,
)
from seldon_core_tpu.contracts.payload import (
    Feedback,
    Meta,
    SeldonError,
    SeldonMessage,
    SeldonMessageList,
)
from seldon_core_tpu.runtime.resilience import (
    BreakerOpen,
    CircuitBreaker,
    Deadline,
    ResilienceConfig,
    ResumeJournal,
    ResumeMarker,
    RetryBudget,
    ShedError,
    current_deadline,
    deadline_scope,
    failure_counts_for_breaker,
)
from seldon_core_tpu.tracing import get_tracer

logger = logging.getLogger(__name__)

TAG_PARTIAL_RESPONSE = "seldon.io/partial-response"
TAG_DROPPED_BRANCHES = "seldon.io/dropped-branches"
TAG_REROUTED = "seldon.io/rerouted"

ComponentFactory = Callable[[PredictiveUnit], SeldonComponent]


class _Suspended(Exception):
    """A graph coroutine suspended on real async work despite
    has_async_nodes=False — the detection heuristic missed an async
    component (e.g. a sync method returning an awaitable, or a callable
    object with async __call__). Callers degrade to the event-loop path."""


def _drive_sync(coro):
    """Run a coroutine that never truly suspends (fully-local graph: every
    await is another such coroutine) to completion without an event loop.
    One send() reaches the first real suspension point — which must not
    exist — or StopIteration with the result."""
    try:
        coro.send(None)
    except StopIteration as stop:
        return stop.value
    coro.close()
    raise _Suspended()


def _is_async_component(comp) -> bool:
    """Does this component's execution leave the process or suspend for real
    (remote endpoint, is_async marker, or any `async def` method)?"""
    if comp is None:
        return False
    from seldon_core_tpu.runtime.remote import RemoteComponent

    if isinstance(comp, RemoteComponent) or getattr(comp, "is_async", False):
        return True
    # _call also supports plain `async def` methods (awaitable
    # results) without the is_async marker — those suspend for real
    for name in ("predict", "transform_input", "transform_output",
                 "route", "aggregate", "send_feedback",
                 "predict_raw", "transform_input_raw",
                 "transform_output_raw", "route_raw",
                 "aggregate_raw", "send_feedback_raw"):
        meth = getattr(comp, name, None)
        if meth is not None and inspect.iscoroutinefunction(meth):
            return True
    return False


def make_puid() -> str:
    """Request id: 26 base32-ish chars, the entropy class of the reference's
    SecureRandom 130-bit id (`service/PredictionService.java:77-83`)."""
    return secrets.token_hex(16)


def replica_load(component: Any) -> Tuple[float, float]:
    """Load score for least-loaded replica dispatch, from the signals the
    serving stack already exports (no new instrumentation): primary = the
    work queued ahead of a new request (admission backlog + occupied
    batcher slots — staged prefill handoffs count HERE, through the
    prefilling slot each remote admission holds until commit/shed, so
    they are not tallied twice off the TransferQueue), secondary = KV
    page-pool pressure (in-use fraction — the shed-proximity signal).
    Components without a batcher score (0, 0): an idle plain component
    is as good a target as an idle LLM replica."""
    svc = getattr(component, "_batcher_service", None)
    if svc is None:
        return (0.0, 0.0)
    b = svc.batcher
    queued = len(b._pending) + sum(
        1 for s in b._slots if s.active or s.prefilling)
    pages = 0.0
    if getattr(b, "paged", False):
        from seldon_core_tpu.models.transformer import RESERVED_PAGES

        total, in_use, _ = b._allocator.stats()
        usable = max(total - RESERVED_PAGES, 1)
        pages = in_use / usable
    return (float(queued), pages)


class _ResumeEntry:
    """One fleet-dispatched generation's recovery record
    (docs/resilience.md "Fleet fault tolerance"): everything needed to
    re-admit it bit-exactly on a surviving replica — identity
    (tenant/SLO class/adapter), the pinned seed, the tokenized prompt,
    and the tokens DELIVERED so far (``len(tokens)`` is also the
    rng-split count to fast-forward by: the chain consumes exactly one
    split per emitted token). Appends happen on batcher worker threads
    while the fleet's retry loop reads — every access goes through
    ``ResumeJournal`` (runtime/resilience.py), which owns the lock."""

    __slots__ = ("prompt_ids", "max_new", "seed", "tenant", "slo_class",
                 "adapter", "tokens")

    def __init__(self, prompt_ids, max_new, seed, tenant, slo_class,
                 adapter):
        self.prompt_ids = prompt_ids
        self.max_new = int(max_new)
        self.seed = seed
        self.tenant = tenant
        self.slo_class = slo_class
        self.adapter = adapter
        self.tokens: List[int] = []


class ReplicaSet(SeldonComponent):
    """N identical component replicas behind least-loaded dispatch — the
    in-process analog of the reference's HPA-scaled Deployment fronted by
    the engine's service (PAPER.md layer map). A predictor unit whose
    registered component is a LIST resolves to one of these: each
    predict/generate picks the replica with the least queued work
    (``replica_load`` — admission queue depth, slot occupancy, staged
    prefill handoffs, page-pool pressure), lowest index breaking ties so
    dispatch is deterministic under equal load. With
    ``disaggregation="remote_prefill"`` replicas, this is the "N decode
    replicas + M prefill workers behind one predictor" topology
    (docs/performance.md "Disaggregated serving").

    Elastic membership (docs/control-plane.md): the autoscaler
    (controlplane/autoscaler.py) grows the set with ``add_replica`` and
    shrinks it with ``drain_replica`` -> ``collect_drained``.  Draining
    is the no-drop half of scale-down: a draining replica leaves the
    dispatch pool IMMEDIATELY (no new fleet traffic), keeps serving its
    queued and in-flight requests to completion, and is detached only
    once provably idle — a scale decision can therefore never fail a
    live request.  Membership mutates under ``self._lock`` (the
    autoscaler thread races transport dispatch threads); dispatch works
    on a locked snapshot so a mid-pick mutation can never index past the
    list.

    Fault tolerance (docs/resilience.md "Fleet fault tolerance"): the
    fleet also survives UNPLANNED departure. ``check_health`` ejects a
    replica whose batcher loop crashed or stopped heartbeating
    (quarantine — distinct from drain: a crashed batcher cannot drain),
    half-open breaker probes reinstate it once it answers again, and the
    per-request resume journal lets every in-flight generation on the
    corpse re-admit on a surviving replica with its rng chain
    fast-forwarded — the client's token sequence is bit-exact vs an
    unfaulted run, with at-most-once delivery. Recoveries draw from a
    RetryBudget so a correlated failure storm sheds honestly instead of
    amplifying fleet load."""

    # transports' service discovery (runtime/batcher.py
    # get_batcher_service): the fleet IS the batcher service — it fans
    # submits across replicas and must never be wrapped in its own batcher
    is_fleet = True

    def __init__(self, replicas: List[SeldonComponent]):
        if not replicas:
            raise SeldonError("ReplicaSet needs >= 1 replica", status_code=500)
        self.replicas = list(replicas)
        self._draining: List[SeldonComponent] = []
        # replicas observed idle on the PREVIOUS collect sweep (by id):
        # detach needs two consecutive idle observations — see
        # collect_drained for the dispatch race this grace absorbs
        self._idle_once: set = set()
        self._lock = threading.Lock()
        # one collect sweep at a time (non-blocking): concurrent sweeps
        # (run_forever tick racing an admin tick) would otherwise count
        # as two consecutive idle sightings microseconds apart —
        # collapsing the grace — and double-close the detached batcher
        self._collect_guard = threading.Lock()
        # -- fleet health (ejection / reinstatement) --------------------
        # injectable clock: chaos tests drive staleness and breaker reset
        # windows from a FaultClock instead of wall time
        self.clock: Callable[[], float] = time.monotonic
        # a batcher whose loop has not stamped its heartbeat for this long
        # (while its task claims to be running) counts as wedged; generous
        # because a first-compile device step legitimately blocks the loop
        self.heartbeat_timeout_s: float = 30.0
        # how long an ejected replica sits out before a half-open probe
        # may try to reinstate it
        self.reinstate_after_s: float = 5.0
        self._health: Dict[int, CircuitBreaker] = {}  # id(replica) -> breaker
        self._ejected: List[SeldonComponent] = []
        self._ejections_total = 0
        self._reinstatements_total = 0
        self._resumes_total = 0
        self._resumed_tokens_total = 0
        # -- deterministic request recovery -----------------------------
        # resume journal: every fleet-dispatched generation in flight,
        # at token granularity (appended from batcher worker threads,
        # read by the retry loop — all locking inside ResumeJournal)
        self._journal = ResumeJournal()
        self.retry_budget = RetryBudget(clock=self.clock)
        self._dispatch_pool = None  # lazy: gRPC submit_stream executor

    # -- membership (autoscaler actuator surface) -----------------------
    def members(self) -> List[SeldonComponent]:
        """Snapshot of every attached replica, draining included (their
        metrics/stats still aggregate until detach)."""
        with self._lock:
            return list(self.replicas)

    def draining_members(self) -> List[SeldonComponent]:
        with self._lock:
            return list(self._draining)

    def _dispatchable(self) -> List[SeldonComponent]:
        """The replicas fleet dispatch may target: everyone not draining
        and not ejected — or, if that empties the pool (a config error
        the autoscaler's min_replicas floor prevents, or a total-fleet
        crash), progressively weaker fallbacks, because black-holing
        traffic is strictly worse than touching a draining replica (and
        submitting to a crashed batcher restarts its loop — the built-in
        half-open probe)."""
        with self._lock:
            live = [r for r in self.replicas
                    if r not in self._draining and r not in self._ejected]
            if live:
                return live
            live = [r for r in self.replicas if r not in self._ejected]
            return live or list(self.replicas)

    def add_replica(self, replica: SeldonComponent) -> None:
        """Attach (and load) one replica; it becomes dispatchable
        immediately."""
        if hasattr(replica, "load"):
            replica.load()
        with self._lock:
            self.replicas.append(replica)

    def drain_replica(self, replica: Optional[SeldonComponent] = None
                      ) -> Optional[SeldonComponent]:
        """Begin draining ``replica`` (default: the newest non-draining
        one — LIFO mirrors the page-shed victim order: the newest member
        has the coldest caches).  Returns the replica now draining, or
        None when nothing is eligible (a lone serving replica never
        drains).  The replica's own ``drain()`` hook (BatcherService /
        ContinuousBatcher) is informed so its admission surface reports
        the state, but its in-flight work keeps running untouched."""
        with self._lock:
            # ejected replicas are not drain candidates: a crashed batcher
            # cannot run the drain protocol (quarantine != drain) — the
            # autoscaler replaces them instead (docs/control-plane.md)
            candidates = [r for r in self.replicas
                          if r not in self._draining
                          and r not in self._ejected]
            if len(candidates) <= 1:
                return None  # the last serving replica never drains
            if replica is None:
                replica = candidates[-1]
            elif replica not in candidates:
                return None
            self._draining.append(replica)
        hook = self._replica_hook(replica, "drain")
        if hook is not None:
            hook()
        return replica

    def undrain_replica(self) -> Optional[SeldonComponent]:
        """Cancel the newest drain (the autoscaler's scale-up-mid-drain
        path): the still-warm replica rejoins dispatch — loaded params,
        hot KV/prefix caches — instead of a cold factory build.  Returns
        the resumed replica, or None when nothing is draining."""
        with self._lock:
            if not self._draining:
                return None
            replica = self._draining.pop()
            self._idle_once.discard(id(replica))
        hook = self._replica_hook(replica, "resume")
        if hook is not None:
            hook()
        return replica

    # -- health model (ejection / reinstatement) ------------------------
    def ejected_members(self) -> List[SeldonComponent]:
        with self._lock:
            return list(self._ejected)

    def _breaker_for(self, replica: SeldonComponent) -> CircuitBreaker:
        """The replica's health breaker (created on first use). Ejected ==
        breaker not CLOSED; reinstatement rides the breaker's half-open
        probe machinery. Breaker methods are never called under
        ``self._lock`` (each breaker has its own lock — a fixed
        fleet-lock-then-breaker-lock order would invert against the
        metrics scrape reading breaker state)."""
        rid = id(replica)
        with self._lock:
            br = self._health.get(rid)
            if br is None:
                br = CircuitBreaker(
                    f"replica-{rid:x}", failure_threshold=3,
                    reset_timeout_s=self.reinstate_after_s,
                    clock=self.clock)
                self._health[rid] = br
        return br

    def _eject(self, replica: SeldonComponent) -> bool:
        with self._lock:
            if replica in self.replicas and replica not in self._ejected:
                self._ejected.append(replica)
                self._ejections_total += 1
                return True
        return False

    def check_health(self) -> List[SeldonComponent]:
        """Eject every replica observed dead: batcher loop crashed
        (terminal exception parked in ``batcher.crashed``) or wedged (its
        task claims to run but the heartbeat the loop stamps every turn
        has gone stale on the fleet clock). Called by the autoscaler tick
        and by fleet dispatch after any failure, so a corpse leaves the
        dispatch pool within one loop turn of dying. Returns the replicas
        ejected by THIS sweep."""
        with self._lock:
            candidates = [r for r in self.replicas
                          if r not in self._ejected]
        dead = []
        for r in candidates:
            svc = getattr(r, "_batcher_service", None)
            if svc is None:
                continue
            b = svc.batcher
            if getattr(b, "crashed", None) is not None:
                dead.append(r)
                continue
            task = getattr(b, "_task", None)
            hb = getattr(b, "heartbeat", None)
            if (task is not None and not task.done() and hb is not None
                    and self.heartbeat_timeout_s > 0
                    and self.clock() - hb > self.heartbeat_timeout_s):
                dead.append(r)
        out = []
        for r in dead:
            self._breaker_for(r).trip()  # observed dead: force-open
            if self._eject(r):
                logger.warning("ejecting dead replica from fleet dispatch")
                out.append(r)
        return out

    def _record_dispatch_success(self, replica: SeldonComponent) -> None:
        """A dispatch answered: close the breaker and, if the replica was
        serving an ejection probe, reinstate it into the pool."""
        self._breaker_for(replica).record_success()
        with self._lock:
            if replica in self._ejected:
                self._ejected.remove(replica)
                self._reinstatements_total += 1

    def _record_dispatch_failure(self, replica: SeldonComponent) -> None:
        """An infrastructure failure from a dispatch: count it on the
        breaker (consecutive failures open it; a failed half-open probe
        re-opens it) and quarantine once the breaker leaves CLOSED."""
        br = self._breaker_for(replica)
        br.record_failure()
        if br.state_code() != 0:  # no longer CLOSED -> quarantine
            self._eject(replica)

    @staticmethod
    def _recoverable(exc: BaseException) -> bool:
        """Which dispatch failures fleet recovery may retry on a sibling:
        infrastructure deaths only. Backpressure (ShedError/BreakerOpen)
        passes through honestly — retrying a shed amplifies exactly the
        load that caused it; client errors (4xx), cancellations and
        timeouts (the original may still be running — a retry would
        double-deliver) are the caller's to see."""
        import concurrent.futures

        if isinstance(exc, (ShedError, BreakerOpen)):
            return False
        if isinstance(exc, (asyncio.CancelledError,
                            concurrent.futures.CancelledError,
                            TimeoutError)):
            return False
        if isinstance(exc, SeldonError):
            return exc.status_code >= 500
        if isinstance(exc, (ValueError, TypeError, KeyError)):
            return False
        return True

    @staticmethod
    def _replica_hook(replica: SeldonComponent, name: str):
        """The replica's drain/is_idle surface: on the component itself,
        else on its batcher service (LLM replicas keep their serving
        state there)."""
        hook = getattr(replica, name, None)
        if hook is not None:
            return hook
        svc = getattr(replica, "_batcher_service", None)
        return getattr(svc, name, None) if svc is not None else None

    def collect_drained(self) -> List[SeldonComponent]:
        """Detach every draining replica that has gone idle (its own
        ``is_idle()`` when exposed, else a zeroed ``replica_load``) and
        close its batcher service.  Replicas still holding work stay
        attached and keep serving it — this is the "let in-flight slots
        finish, then detach" half of the drain contract.

        Detach needs TWO consecutive idle sweeps plus an idle re-check
        after removal (with reattach on failure): a dispatcher that
        picked this replica just before the drain could submit after a
        single idle observation, and closing under it would fail a live
        request.  The grace bounds the remaining exposure to a pick held
        across two full autoscaler ticks — and even that tail is
        retryable, not fatal (a closed batcher sheds 503+Retry-After
        back through routing).  One sweep runs at a time (concurrent
        callers return [] immediately): overlapping sweeps would count
        two "consecutive" sightings in one instant and detach twice."""
        if not self._collect_guard.acquire(blocking=False):
            return []
        try:
            return self._collect_locked()
        finally:
            self._collect_guard.release()

    def _collect_locked(self) -> List[SeldonComponent]:
        with self._lock:
            draining = list(self._draining)
        done = []
        for r in draining:
            idle_fn = self._replica_hook(r, "is_idle")

            def idle() -> bool:
                return idle_fn() if idle_fn is not None else \
                    replica_load(r) == (0.0, 0.0)

            if not idle():
                with self._lock:
                    self._idle_once.discard(id(r))
                continue
            with self._lock:
                if id(r) not in self._idle_once:
                    self._idle_once.add(id(r))  # first sighting: grace
                    first_sighting = True
                else:
                    first_sighting = False
            if first_sighting:
                continue
            with self._lock:
                if r in self.replicas:
                    self.replicas.remove(r)
                if r in self._draining:
                    self._draining.remove(r)
            if not idle():
                # a submit landed between the sweep check and removal:
                # reattach and try again next tick — never close under it
                with self._lock:
                    self.replicas.append(r)
                    self._draining.append(r)
                    self._idle_once.discard(id(r))
                continue
            with self._lock:
                self._idle_once.discard(id(r))
            svc = getattr(r, "_batcher_service", None)
            if svc is not None:
                try:
                    svc.close()
                except Exception:  # detaching must not fail the tick
                    logger.exception("closing drained replica's batcher")
            done.append(r)
        return done

    def load(self) -> None:
        for r in self.members():
            if hasattr(r, "load"):
                r.load()

    def pick(self) -> SeldonComponent:
        """The least-loaded dispatchable replica right now (scores re-read
        per call — the signals mutate under their own locks on the
        serving path)."""
        reps = self._dispatchable()
        best, best_score = reps[0], replica_load(reps[0])
        for r in reps[1:]:
            score = replica_load(r)
            if score < best_score:
                best, best_score = r, score
        return best

    def pick_for(self, prompt: Any) -> SeldonComponent:
        """Prefix-aware dispatch for chat traffic: the replica whose radix
        prefix cache (runtime/radix.py) already holds the LONGEST cached
        prefix of ``prompt`` wins — a hit there costs block-table entries
        while any other replica recomputes the whole prefill — with
        least-loaded as tiebreak and as fallback when nobody caches
        anything (``prefix_match_len`` is an O(prompt) host-side probe
        under the replica's own locks: cheap enough to run per dispatch).
        Lowest index breaks full ties so routing stays deterministic."""
        reps = self._dispatchable()
        prompt = self._encode_once(prompt, reps)
        best, best_key = None, None
        for i, r in enumerate(reps):
            match = 0
            probe = getattr(r, "prefix_match_len", None)
            if probe is not None and prompt is not None:
                match = int(probe(prompt))
            key = (-match, replica_load(r), i)
            if best_key is None or key < best_key:
                best, best_key = r, key
        return best

    def _encode_once(self, prompt: Any,
                     reps: Optional[List[SeldonComponent]] = None):
        """Tokenize a string prompt ONCE before fanning the probe out —
        per-replica `prefix_match_len(str)` would re-encode a growing
        chat transcript N times per dispatch (replicas share the
        tokenizer config by construction; a replica without one just
        gets the raw prompt)."""
        if not isinstance(prompt, str):
            return prompt
        for r in (reps if reps is not None else self.members()):
            tok = getattr(r, "_tokenizer", None)
            if tok is not None:
                return tok.encode(prompt)
        return prompt

    def loads(self) -> List[Tuple[float, float]]:
        return [replica_load(r) for r in self.members()]

    def prefix_match_len(self, prompt: Any) -> int:
        """Fleet-level probe: the best cached-prefix length any replica
        offers (lets ReplicaSets nest / upstream routers see the fleet's
        coverage as one number)."""
        reps = self.members()
        prompt = self._encode_once(prompt, reps)
        out = 0
        for r in reps:
            probe = getattr(r, "prefix_match_len", None)
            if probe is not None:
                out = max(out, int(probe(prompt)))
        return out

    # -- fleet batcher-service protocol ---------------------------------
    # The transports reach LLM serving through get_batcher_service /
    # ensure_stream_service (runtime/batcher.py), which short-circuit to
    # the fleet itself: submit/submit_sync/submit_stream here mirror
    # BatcherService's surface but fan across replicas with journaled
    # deterministic recovery (docs/resilience.md "Fleet fault tolerance").

    @property
    def batcher(self):
        """Transports call ``svc.batcher.accommodates`` — the fleet
        answers for itself."""
        return self

    def accommodates(self, prompt: Any,
                     max_new_tokens: Optional[int] = None) -> bool:
        """Delegates to one dispatchable replica's batcher (replicas are
        identical by construction, so one answer speaks for the set)."""
        from seldon_core_tpu.runtime.batcher import ensure_stream_service

        for r in self._dispatchable():
            if hasattr(r, "generate"):
                return ensure_stream_service(r).batcher.accommodates(
                    prompt, max_new_tokens)
        return False

    async def submit(self, prompt: Any, max_new_tokens: Optional[int] = None,
                     on_token: Optional[Any] = None,
                     info: Optional[dict] = None,
                     seed: Optional[int] = None,
                     trace: Optional[Any] = None,
                     tenant: Optional[str] = None,
                     slo_class: Optional[str] = None,
                     adapter: Optional[str] = None,
                     deadline_s: Optional[float] = None,
                     resume_tokens: int = 0) -> List[int]:
        return await asyncio.to_thread(
            self._fleet_submit_blocking, prompt, max_new_tokens, on_token,
            info, seed, trace, tenant, slo_class, adapter, deadline_s)

    def submit_sync(self, prompt: Any, max_new_tokens: Optional[int] = None,
                    timeout_s: float = 600.0,
                    info: Optional[dict] = None,
                    seed: Optional[int] = None,
                    trace: Optional[Any] = None,
                    tenant: Optional[str] = None,
                    slo_class: Optional[str] = None,
                    adapter: Optional[str] = None,
                    deadline_s: Optional[float] = None,
                    on_token: Optional[Any] = None,
                    resume_tokens: int = 0) -> List[int]:
        return self._fleet_submit_blocking(
            prompt, max_new_tokens, on_token, info, seed, trace, tenant,
            slo_class, adapter, deadline_s, timeout_s=timeout_s)

    def submit_stream(self, prompt: Any,
                      max_new_tokens: Optional[int] = None,
                      on_token: Optional[Any] = None,
                      info: Optional[dict] = None,
                      seed: Optional[int] = None,
                      trace: Optional[Any] = None,
                      tenant: Optional[str] = None,
                      slo_class: Optional[str] = None,
                      adapter: Optional[str] = None,
                      deadline_s: Optional[float] = None,
                      resume_tokens: int = 0):
        """Streaming submit from a sync thread (the gRPC servicer):
        returns a concurrent.futures.Future of the final token list while
        ``on_token`` pumps — same contract as BatcherService."""
        with self._lock:
            pool = self._dispatch_pool
            if pool is None:
                from concurrent.futures import ThreadPoolExecutor

                pool = ThreadPoolExecutor(
                    max_workers=32, thread_name_prefix="fleet-dispatch")
                self._dispatch_pool = pool
        return pool.submit(
            self._fleet_submit_blocking, prompt, max_new_tokens, on_token,
            info, seed, trace, tenant, slo_class, adapter, deadline_s)

    def _pick_with_probe(self, prompt: Any
                         ) -> Tuple[SeldonComponent, bool]:
        """Dispatch target for one attempt: an ejected replica whose
        breaker grants a half-open probe slot wins (reinstatement rides
        real traffic — the retry loop absorbs a failed probe), otherwise
        prefix-aware least-loaded routing over the healthy pool."""
        with self._lock:
            ejected = list(self._ejected)
        for r in ejected:
            if self._breaker_for(r).allow():
                return r, True
        return self.pick_for(prompt), False

    def _fleet_submit_blocking(self, prompt: Any,
                               max_new_tokens: Optional[int] = None,
                               on_token: Optional[Any] = None,
                               info: Optional[dict] = None,
                               seed: Optional[int] = None,
                               trace: Optional[Any] = None,
                               tenant: Optional[str] = None,
                               slo_class: Optional[str] = None,
                               adapter: Optional[str] = None,
                               deadline_s: Optional[float] = None,
                               timeout_s: float = 600.0) -> List[int]:
        """One fleet generation, end to end: journal it, dispatch to the
        best replica, and on an infrastructure death resume the
        interrupted chain bit-exactly on a survivor.

        Determinism: an unseeded request gets a journaled random seed
        BEFORE first dispatch, so greedy and sampled generations alike
        live on one pinned rng chain that a resume can fast-forward
        (batcher._sample_first). The ``ResumeJournal`` records each token
        under its lock BEFORE forwarding it to the client, so a resume
        skips exactly the delivered prefix — at-most-once delivery, never
        a duplicate. The batcher's crash handler fires ``on_token(None)``
        at its victims; the wrapper swallows it (the fleet owns the
        terminal None) so a streaming client survives the failover
        without observing a premature end-of-stream."""
        from seldon_core_tpu.runtime.batcher import ensure_stream_service

        self.check_health()
        self.retry_budget.note_request()
        reps = self._dispatchable()
        ids = self._encode_once(prompt, reps)
        can_resume = not isinstance(ids, str)
        prompt_ids = (list(int(t) for t in np.asarray(ids).ravel())
                      if can_resume else ids)
        if max_new_tokens is None:
            for r in reps:
                mn = getattr(r, "max_new_tokens", None)
                if mn is not None:
                    max_new_tokens = int(mn)
                    break
        orig_max_new = int(max_new_tokens or 16)
        if seed is None:
            # pin the chain so a resume can replay it (greedy output is
            # seed-independent; unseeded SAMPLED fleet output was random
            # anyway — now it is random-but-resumable)
            seed = secrets.randbits(31)
        entry = _ResumeEntry(prompt_ids, orig_max_new, seed,
                             tenant, slo_class, adapter)
        jid = self._journal.record(entry)

        def wrapped(tok):
            if tok is None:
                return  # crash-handler unblock: the fleet owns the real one
            if isinstance(tok, ResumeMarker):
                if on_token is not None:
                    on_token(tok)
                return
            self._journal.append(jid, int(tok))
            if on_token is not None:
                on_token(tok)

        try:
            while True:
                done = self._journal.delivered(jid)
                n = len(done)
                if n >= orig_max_new:
                    return done  # the crash raced completion
                if n > 0:
                    submit_ids = prompt_ids + done
                    remaining = orig_max_new - n
                else:
                    submit_ids, remaining = prompt_ids, orig_max_new
                replica, probing = self._pick_with_probe(submit_ids)
                if n > 0:
                    self._note_resume(n, trace)
                    wrapped(ResumeMarker(n))
                try:
                    svc = ensure_stream_service(replica)
                    toks = svc.submit_sync(
                        submit_ids, remaining, timeout_s=timeout_s,
                        info=info, seed=seed, trace=trace, tenant=tenant,
                        slo_class=slo_class, adapter=adapter,
                        deadline_s=deadline_s, on_token=wrapped,
                        resume_tokens=n)
                except BaseException as e:
                    if probing:
                        self._breaker_for(replica).release_probe()
                    if not self._recoverable(e):
                        raise
                    self._record_dispatch_failure(replica)
                    self.check_health()  # a crash ejects before the retry
                    delivered = len(self._journal.delivered(jid))
                    if delivered > 0 and not can_resume:
                        raise  # mid-stream, no token-level journal: honest
                    if not self.retry_budget.take():
                        raise ShedError(
                            "fleet retry budget exhausted (correlated "
                            "failures); request not recovered",
                            retry_after_s=self.reinstate_after_s)
                    continue
                self._record_dispatch_success(replica)
                # the replica's returned segment is authoritative for the
                # tail (on_token elides EOS; the result never does)
                return done + [int(t) for t in toks]
        finally:
            self._journal.discard(jid)
            if on_token is not None:
                try:
                    on_token(None)
                except Exception:
                    pass

    def _note_resume(self, tokens_delivered: int,
                     trace: Optional[Any]) -> None:
        """Count + trace one mid-stream recovery (``llm.resume`` span)."""
        with self._lock:
            self._resumes_total += 1
            self._resumed_tokens_total += tokens_delivered
        tp = None
        if trace is not None and getattr(trace, "trace_id", None):
            span_id = getattr(trace, "parent_span_id", None) or "0" * 16
            flag = "01" if getattr(trace, "sampled", True) else "00"
            tp = f"00-{trace.trace_id}-{span_id}-{flag}"
        with get_tracer().span("llm.resume", traceparent=tp,
                               tokens_delivered=tokens_delivered):
            pass

    # the component surface delegates to the chosen replica; generate is
    # included so LLM graph nodes (and their transports) route too
    def predict(self, X, names, meta=None):
        return self.pick().predict(X, names, meta)

    def generate(self, prompts=None, *a, **kw):
        # route on the FIRST prompt's cached-prefix coverage (single-
        # prompt requests are the chat shape prefix routing exists for;
        # multi-prompt batches still benefit from the first's locality)
        probe = None
        if prompts is not None and len(prompts) > 0:
            probe = prompts[0]
        self.retry_budget.note_request()
        replica = self.pick() if probe is None else self.pick_for(probe)
        try:
            out = replica.generate(prompts, *a, **kw)
        except Exception as e:
            # pre-first-token failover (ISSUE 16 satellite): generate()
            # had not delivered anything, so retrying the WHOLE call on a
            # healthy sibling is idempotent by construction — once, and
            # only from the bounded retry budget
            if not self._recoverable(e):
                raise
            self._record_dispatch_failure(replica)
            self.check_health()
            siblings = [r for r in self._dispatchable() if r is not replica]
            if not siblings:
                raise
            if not self.retry_budget.try_spend():
                raise ShedError(
                    "fleet retry budget exhausted (correlated failures); "
                    "generate not failed over",
                    retry_after_s=self.reinstate_after_s)
            alt = min(siblings, key=replica_load)
            out = alt.generate(prompts, *a, **kw)
            self._record_dispatch_success(alt)
            return out
        self._record_dispatch_success(replica)
        return out

    def tags(self) -> Dict[str, Any]:
        from seldon_core_tpu.components.component import client_custom_tags

        reps = self.members()
        out: Dict[str, Any] = {"replicas": len(reps)}
        for i, r in enumerate(reps):
            for k, v in client_custom_tags(r).items():
                out[f"replica_{i}_{k}"] = v
        return out

    def llm_stats(self) -> Dict[str, Any]:
        """Aggregated snapshot for /metrics: numeric gauges/counters sum,
        drained lists concatenate (each replica's deques drain exactly
        once, same as solo), strings/configs come from replica 0."""
        stats_list = [r.llm_stats() for r in self.members()
                      if hasattr(r, "llm_stats")]
        if not stats_list:
            return {}
        fractions = ("kv_occupancy", "kv_page_fragmentation",
                     "spec_accept_rate", "spec_tokens_per_forward",
                     "spec_draft_overhead_fraction")
        merged = dict(stats_list[0])
        for stats in stats_list[1:]:
            for k, v in stats.items():
                cur = merged.get(k)
                if isinstance(v, list) and isinstance(cur, list):
                    merged[k] = cur + v
                elif isinstance(v, (int, float)) and isinstance(
                        cur, (int, float)) and not isinstance(v, bool):
                    merged[k] = cur + v
        for k in fractions:  # fractions average; sums would exceed 1.0
            if isinstance(merged.get(k), (int, float)):
                merged[k] = merged[k] / len(stats_list)
        # fleet-level fault-tolerance tallies (ours, not the replicas'):
        # stamped AFTER the merge so a replica key can never shadow them
        with self._lock:
            merged["fleet_ejections_total"] = self._ejections_total
            merged["fleet_reinstatements_total"] = self._reinstatements_total
            merged["fleet_resumes_total"] = self._resumes_total
            merged["fleet_resumed_tokens_total"] = self._resumed_tokens_total
        merged["fleet_resume_journal_depth"] = self._journal.depth()
        merged["fleet_retry_budget_exhausted_total"] = (
            self.retry_budget.snapshot()["exhausted_total"])
        return merged


@dataclass
class UnitState:
    """Built (static) state for one graph node: resolved component + children.

    Equivalent of `engine/.../PredictiveUnitState.java:37-125`, constructed
    once at engine build, never per request.
    """

    name: str
    unit: PredictiveUnit
    component: Optional[SeldonComponent]
    children: List["UnitState"] = field(default_factory=list)
    image: str = ""
    # Per-node circuit breaker; built only for remote/async nodes (local
    # in-process calls cannot flake independently of the server itself).
    breaker: Optional[CircuitBreaker] = None
    # Set when this node's entire subtree fused into one jitted callable.
    fused_fn: Optional[Callable[[Any], Any]] = None
    # All units covered by fused_fn, and the component whose class_names/
    # encoding rules own the final payload (the last node in unfused flow).
    fused_units: List["UnitState"] = field(default_factory=list)
    fused_owner: Optional[SeldonComponent] = None

    @property
    def methods(self) -> List[UnitMethod]:
        return self.unit.resolved_methods()

    def has_method(self, m: UnitMethod) -> bool:
        return m in self.methods


class PredictorState:
    """Immutable built graph for one predictor."""

    def __init__(self, spec: PredictorSpec, root: UnitState):
        self.spec = spec
        self.root = root

    def walk(self):
        stack = [self.root]
        while stack:
            s = stack.pop()
            yield s
            stack.extend(s.children)

    def unit_by_name(self, name: str) -> Optional[UnitState]:
        for s in self.walk():
            if s.name == name:
                return s
        return None


class GraphEngine:
    """Builds and executes a predictor graph.

    components: name -> live SeldonComponent for in-process user nodes.
    factory: fallback resolver for units this engine cannot resolve itself
             (used by servers/ to wire prepackaged servers from modelUri).
    """

    def __init__(
        self,
        spec: PredictorSpec,
        components: Optional[Dict[str, SeldonComponent]] = None,
        factory: Optional[ComponentFactory] = None,
        fuse: bool = True,
        remote_client: Optional[Any] = None,
        annotations: Optional[Dict[str, str]] = None,
        resilience: Optional[ResilienceConfig] = None,
    ):
        self.spec = spec
        self._components = dict(components or {})
        self._factory = factory
        self._fuse = fuse
        self._remote_client = remote_client
        # deployment annotations tune the remote-node client (retry counts,
        # connect/read deadlines — the reference's per-deployment flags)
        self._annotations = dict(annotations or {})
        self.resilience = resilience or ResilienceConfig.from_annotations(self._annotations)
        self.state = self._build(spec)
        if fuse:
            self._try_fuse(self.state.root)
        # A graph whose every node is local+synchronous never truly suspends:
        # predict()/send_feedback() coroutines run to completion without an
        # event loop (the only awaits are child coroutines and — avoided
        # below for this case — asyncio.gather). The IPC drain uses this to
        # execute plane-3 frames inline on its own thread, skipping the
        # event-loop hop entirely.
        self.has_async_nodes = any(
            _is_async_component(s.component) for s in self.state.walk()
        )
        # Breakers wrap remote/async node calls only: a purely local call
        # cannot fail independently of this process, so a breaker there would
        # just add lock traffic to the fused hot path.
        for s in self.state.walk():
            if _is_async_component(s.component):
                s.breaker = self.resilience.make_breaker(s.name)

    def breakers(self) -> List[Tuple[str, CircuitBreaker]]:
        """(node name, breaker) for every breaker-wrapped node, stable order
        — the metrics scrape walks this to publish state gauges."""
        out = [(s.name, s.breaker) for s in self.state.walk() if s.breaker is not None]
        return sorted(out, key=lambda kv: kv[0])

    # ------------------------------------------------------------------
    # Build
    # ------------------------------------------------------------------
    def _build(self, spec: PredictorSpec) -> PredictorState:
        root = self._build_unit(spec.graph)
        return PredictorState(spec, root)

    def _build_unit(self, unit: PredictiveUnit) -> UnitState:
        component = self._resolve(unit)
        image = type(component).__name__ if component is not None else (
            f"{unit.endpoint.service_host}:{unit.endpoint.service_port}" if unit.endpoint else ""
        )
        state = UnitState(
            name=unit.name,
            unit=unit,
            component=component,
            children=[self._build_unit(c) for c in unit.children],
            image=image,
        )
        return state

    def _resolve(self, unit: PredictiveUnit) -> Optional[SeldonComponent]:
        if unit.name in self._components:
            comp = self._components[unit.name]
            if isinstance(comp, (list, tuple)):
                # a list of components registers N replicas behind
                # least-loaded dispatch; cache the wrapper so repeated
                # builds (and the metrics scrape walking _components)
                # see ONE ReplicaSet, not one per resolve
                comp = ReplicaSet(list(comp))
                self._components[unit.name] = comp
        elif unit.implementation is not None and unit.implementation not in (
            UnitImplementation.UNKNOWN_IMPLEMENTATION,
        ):
            comp = self._make_implementation(unit)
        elif unit.endpoint is not None and unit.endpoint.service_host:
            from seldon_core_tpu.runtime.remote import RemoteComponent

            comp = RemoteComponent(
                unit.endpoint, client=self._remote_client,
                annotations=self._annotations or None,
            )
        elif self._factory is not None:
            comp = self._factory(unit)
        else:
            raise SeldonError(
                f"Cannot resolve component for unit {unit.name!r}: no registered component, "
                f"implementation, or endpoint",
                reason="BAD_GRAPH",
                status_code=500,
            )
        if comp is not None and hasattr(comp, "load"):
            comp.load()
        return comp

    def _make_implementation(self, unit: PredictiveUnit) -> SeldonComponent:
        impl = unit.implementation
        params = unit.parameters_dict()
        try:
            return make_builtin(impl, params)
        except ValueError:
            pass
        from seldon_core_tpu.servers import make_prepackaged_server

        return make_prepackaged_server(impl, unit.model_uri, params)

    # ------------------------------------------------------------------
    # Whole-graph XLA fusion
    # ------------------------------------------------------------------
    def _try_fuse(self, state: UnitState):
        """Bottom-up: if this node and all children are pure jax fns (and no
        routing decision is needed), produce one jitted callable for the
        subtree. Returns (fn, covered_units, owner) or None. Falls back
        silently; correctness never depends on fusion."""
        child_results = [self._try_fuse(c) for c in state.children]

        fusible = (
            state.component is not None
            and not state.has_method(UnitMethod.ROUTE)
            and all(r is not None for r in child_results)
        )
        if not fusible:
            return None
        pair = state.component.jax_fn() if hasattr(state.component, "jax_fn") else None
        if pair is None:
            return None
        fn, params = pair

        is_combiner = state.has_method(UnitMethod.AGGREGATE)
        if is_combiner and not state.children:
            # A leaf combiner aggregates a singleton list of the request (the
            # unfused path's behavior); fusing fn(x) directly would instead
            # reduce over the batch dim. Leave it to the host path.
            return None
        if state.children and not is_combiner and len(state.children) > 1:
            return None  # multiple children need a combiner to merge

        import jax
        import jax.numpy as jnp

        if not state.children:
            covered = [state]
            owner = state.component

            def subtree(x, _fn=fn, _p=params):
                return _fn(_p, x)
        elif is_combiner:
            children = [r[0] for r in child_results]
            covered = [state] + [u for r in child_results for u in r[1]]
            owner = state.component  # combiner constructs the merged response

            def subtree(x, _fn=fn, _p=params, _children=children):
                outs = [c(x) for c in _children]
                return _fn(_p, jnp.stack(outs))
        else:
            # transformer/model with a single child: this node transforms the
            # input, the child consumes it and owns the response.
            child, child_units, child_owner = child_results[0]
            covered = [state] + child_units
            owner = child_owner

            def subtree(x, _fn=fn, _p=params, _child=child):
                return _child(_fn(_p, x))

        # Only install a fused executor for MULTI-node subtrees: fusing a lone
        # leaf adds a per-request jit dispatch (and, on this harness, a device
        # round trip) without merging anything — components run their own
        # compiled path (e.g. JAXServer) or host path (stubs) when unfused.
        # The (fn, covered, owner) return still flows upward so a parent can
        # fuse this leaf into a larger program.
        if len(covered) >= 2:
            state.fused_fn = jax.jit(subtree)
            state.fused_units = covered
            state.fused_owner = owner
            logger.info("fused %d-unit subtree at %s into one XLA computation", len(covered), state.name)
        return subtree, covered, owner

    # ------------------------------------------------------------------
    # Predict
    # ------------------------------------------------------------------
    async def predict(
        self, request: SeldonMessage, deadline: Optional[Deadline] = None
    ) -> SeldonMessage:
        if not request.meta.puid:
            request.meta.puid = make_puid()
        puid = request.meta.puid
        # Deadline resolution: explicit arg > transport-set contextvar >
        # deployment default annotation. The scope re-publishes it on the
        # contextvar so remote hops see the budget regardless of which path
        # delivered it.
        if deadline is None:
            deadline = current_deadline()
        if deadline is None and self.resilience.default_deadline_ms:
            deadline = Deadline.from_ms(
                self.resilience.default_deadline_ms, clock=self.resilience.clock
            )
        with deadline_scope(deadline):
            response = await self._get_output(self.state.root, request)
        response.meta.puid = puid
        return response

    def predict_sync(self, request: SeldonMessage) -> SeldonMessage:
        if self.has_async_nodes:
            return asyncio.run(self.predict(request))
        try:
            return _drive_sync(self.predict(request))
        except _Suspended:
            self._degrade_to_async("predict")
            return asyncio.run(self.predict(request))

    def send_feedback_sync(self, feedback: "Feedback") -> SeldonMessage:
        if self.has_async_nodes:
            return asyncio.run(self.send_feedback(feedback))
        try:
            return _drive_sync(self.send_feedback(feedback))
        except _Suspended:
            self._degrade_to_async("send_feedback")
            return asyncio.run(self.send_feedback(feedback))

    def _degrade_to_async(self, op: str) -> None:
        """Async-detection miss (a component's sync method returned an
        awaitable, or an async __call__ object slipped past the
        iscoroutinefunction check): flip the graph to the event-loop path
        permanently so this and every later request runs there instead of
        500ing.

        Caveat, by design: the aborted inline attempt already executed every
        node UPSTREAM of the suspension point, and the retry re-executes
        them — for this one degraded request, side-effectful upstream
        components (feedback counters, external calls) fire twice. The
        alternative (500 after the same partial execution, every request)
        is strictly worse; the log below makes the one-time re-execution
        auditable."""
        logger.warning(
            "graph suspended on real async work during sync %s despite "
            "has_async_nodes=False; degrading to the event-loop path. "
            "Nodes upstream of the suspension re-execute for this request "
            "(side effects may fire twice, once).", op)
        self.has_async_nodes = True

    async def _get_output(self, state: UnitState, message: SeldonMessage) -> SeldonMessage:
        # Budget check BEFORE executing this node: an exhausted deadline
        # short-circuits the remaining subtree with 504 instead of doing work
        # the client has already given up on.
        deadline = current_deadline()
        if deadline is not None:
            deadline.check(f"node {state.name}")

        # Fused fast path: the whole subtree is one XLA call. Meta parity with
        # the unfused flow: every covered unit contributes its requestPath
        # entry and tags/metrics; the flow-final component owns the payload
        # encoding and class_names.
        if state.fused_fn is not None and message.which == "data" and message.data is not None:
            arr = message.data.to_numpy()
            out = state.fused_fn(np.asarray(arr, dtype=np.float32) if arr.dtype != np.float32 else arr)
            resp = dispatch.construct_response(state.fused_owner or state.component, False, message, out)
            self._merge_meta(resp, message.meta)
            from seldon_core_tpu.codec.response import response_meta

            for unit in state.fused_units:
                if unit.component is not state.fused_owner:
                    self._merge_meta(resp, response_meta(unit.component, None))
                self._record_path(resp, unit)
            return resp

        # 1. transformInput (for MODEL this is predict — the reference maps
        #    MODEL.transformInput to the predict method,
        #    `PredictorConfigBean.java:30-107`).
        if state.has_method(UnitMethod.TRANSFORM_INPUT):
            if state.unit.type == UnitType.MODEL:
                transformed = await self._call(dispatch.predict, state, message)
            else:
                transformed = await self._call(dispatch.transform_input, state, message)
            self._merge_meta(transformed, message.meta)
        else:
            transformed = message

        # 2. route
        branch = -1
        if state.has_method(UnitMethod.ROUTE) and state.children:
            route_msg = await self._call(dispatch.route, state, transformed)
            branch = dispatch.extract_route(route_msg)
            if branch >= len(state.children):
                raise SeldonError(
                    f"Router {state.name} returned branch {branch} but unit has "
                    f"{len(state.children)} children",
                    status_code=500,
                    reason="BAD_ROUTING",
                )
            if branch >= 0:
                # graceful degradation: reroute away from a branch whose
                # subtree has an open breaker, onto the healthiest sibling
                healthy = self._healthy_branch(state, branch)
                if healthy != branch:
                    logger.warning(
                        "router %s: branch %d unavailable (breaker open), rerouting to %d",
                        state.name, branch, healthy,
                    )
                    rerouted = dict(transformed.meta.tags.get(TAG_REROUTED) or {})
                    rerouted[state.name] = {"from": branch, "to": healthy}
                    transformed.meta.tags[TAG_REROUTED] = rerouted
                    branch = healthy
            transformed.meta.routing[state.name] = branch
            self._merge_meta(transformed, route_msg.meta, routing_only_tags=True)

        # 3. children
        dropped_branches: List[str] = []
        if state.children:
            if branch == -1:
                allow_partial = (
                    self.resilience.allow_partial
                    and state.has_method(UnitMethod.AGGREGATE)
                    and len(state.children) > 1
                )
                if self.has_async_nodes:
                    results = await asyncio.gather(
                        *[self._get_output(c, transformed) for c in state.children],
                        return_exceptions=allow_partial,
                    )
                else:
                    # local components are synchronous: gather buys no
                    # concurrency here, only Task/loop overhead — and
                    # avoiding it keeps the whole coroutine loop-free so
                    # predict_sync can drive it without an event loop
                    results = []
                    for c in state.children:
                        if not allow_partial:
                            results.append(await self._get_output(c, transformed))
                            continue
                        try:
                            results.append(await self._get_output(c, transformed))
                        except SeldonError as e:
                            results.append(e)
                child_outputs = []
                for child, r in zip(state.children, results):
                    if isinstance(r, BaseException):
                        # allow-partial drops only branches rejected by an
                        # open breaker; real execution failures still fail
                        # the request (partial data, yes — silent data loss
                        # from crashing nodes, no)
                        if isinstance(r, BreakerOpen):
                            dropped_branches.append(child.name)
                            continue
                        raise r
                    child_outputs.append(r)
                if state.children and not child_outputs and dropped_branches:
                    raise SeldonError(
                        f"combiner {state.name}: every branch dropped by open "
                        f"circuit breakers ({', '.join(dropped_branches)})",
                        status_code=503,
                        reason="CIRCUIT_OPEN",
                    )
            else:
                # Routed-branch outcome observation: routers exposing
                # ``observe_outcome(branch, latency_s, error)`` (the canary
                # router, analytics/canary.py) see every routed request's
                # subtree wall + error on the engine's INJECTABLE clock —
                # which is what makes SLO comparison deterministic under
                # FaultClock (tests/test_canary.py). Absent the hook this
                # is one getattr per routed request.
                observe = getattr(state.component, "observe_outcome", None)
                if observe is None:
                    child_outputs = [await self._get_output(
                        state.children[branch], transformed)]
                else:
                    t0 = self.resilience.clock()
                    try:
                        child_outputs = [await self._get_output(
                            state.children[branch], transformed)]
                    except asyncio.CancelledError:
                        # client disconnect says nothing about the branch
                        # (the breaker rule, failure_counts_for_breaker):
                        # a disconnect burst during a canary must not
                        # land spurious errors in the candidate's small
                        # window and roll back a healthy candidate
                        raise
                    except BaseException:
                        self._observe_routed(
                            observe, branch, self.resilience.clock() - t0,
                            True)
                        raise
                    self._observe_routed(
                        observe, branch, self.resilience.clock() - t0, False)
        else:
            child_outputs = []

        # 4. aggregate / merge
        if state.has_method(UnitMethod.AGGREGATE):
            if not child_outputs:
                child_outputs = [transformed]
            merged = await self._call(
                dispatch.aggregate, state, SeldonMessageList(messages=list(child_outputs))
            )
            for co in child_outputs:
                self._merge_meta(merged, co.meta)
            if dropped_branches:
                merged.meta.tags[TAG_PARTIAL_RESPONSE] = True
                merged.meta.tags[TAG_DROPPED_BRANCHES] = list(dropped_branches)
        elif len(child_outputs) == 1:
            merged = child_outputs[0]
        elif len(child_outputs) > 1:
            raise SeldonError(
                f"Unit {state.name} has {len(child_outputs)} child outputs but no "
                f"COMBINER to aggregate them",
                status_code=500,
                reason="BAD_GRAPH",
            )
        else:
            merged = transformed

        # 5. transformOutput
        if state.has_method(UnitMethod.TRANSFORM_OUTPUT):
            out = await self._call(dispatch.transform_output, state, merged)
            self._merge_meta(out, merged.meta)
        else:
            out = merged

        self._record_path(out, state)
        return out

    @staticmethod
    def _observe_routed(observe, branch: int, latency_s: float,
                        error: bool) -> None:
        """Feed a routed request's outcome to the router's observation
        hook; observability must never fail the data path."""
        try:
            observe(branch, latency_s, error=error)
        except Exception:
            logger.exception("router observe_outcome hook failed")

    @staticmethod
    def _subtree_available(state: UnitState) -> bool:
        """Non-mutating: is every breaker-wrapped node in this subtree
        currently accepting calls? Routers peek at this before committing a
        request to a branch."""
        stack = [state]
        while stack:
            s = stack.pop()
            if s.breaker is not None and not s.breaker.available():
                return False
            stack.extend(s.children)
        return True

    def _healthy_branch(self, state: UnitState, branch: int) -> int:
        """The routed branch if its subtree is healthy, else the lowest-index
        sibling with no open breakers. All-unhealthy keeps the original
        routing decision (it then fails with CIRCUIT_OPEN, which is the
        honest answer)."""
        if self._subtree_available(state.children[branch]):
            return branch
        for i, child in enumerate(state.children):
            if i != branch and self._subtree_available(child):
                return i
        return branch

    async def _call(self, fn: Callable, state: UnitState, message: Any) -> SeldonMessage:
        comp = state.component
        if comp is None:
            raise SeldonError(f"Unit {state.name} has no component", status_code=500)
        breaker = state.breaker
        if breaker is not None and not breaker.allow():
            raise BreakerOpen(state.name, breaker.retry_in_s())
        # per-node child span (the reference's engine->graph-node topology,
        # PAPER.md §5): parented to the transport's server span via the
        # tracer's contextvar, so a remote node's outbound traceparent
        # (runtime/remote.py) carries this node's span id downstream. A
        # disabled tracer yields None immediately — no per-node cost.
        with get_tracer().span(f"node:{state.name}",
                               method=getattr(fn, "__name__", "")):
            try:
                if getattr(comp, "is_async", False):
                    result = await fn(comp, message)
                else:
                    result = fn(comp, message)
                    if inspect.isawaitable(result):
                        result = await result
            except BaseException as e:
                # Every outcome must resolve a half-open probe, or the breaker
                # wedges with its one probe slot held forever. Counting failures
                # re-open; cancellation judges nothing (release the slot); any
                # other error means the node RESPONDED (4xx and kin) — healthy.
                if breaker is not None:
                    if failure_counts_for_breaker(e):
                        breaker.record_failure()
                    elif isinstance(e, asyncio.CancelledError):
                        breaker.release_probe()
                    else:
                        breaker.record_success()
                raise
        if breaker is not None:
            breaker.record_success()
        return result

    @staticmethod
    def _merge_meta(target: SeldonMessage, source: Meta, routing_only_tags: bool = False) -> None:
        """Merge request/previous meta into a node response, per the reference's
        mergeMeta (`PredictiveUnitBean.java:350-366`): tags union (response
        wins), routing/requestPath union, metrics append."""
        merged_tags = dict(source.tags)
        merged_tags.update(target.meta.tags)
        target.meta.tags = merged_tags
        for k, v in source.routing.items():
            target.meta.routing.setdefault(k, v)
        for k, v in source.request_path.items():
            target.meta.request_path.setdefault(k, v)
        if not routing_only_tags:
            existing = {id(m) for m in target.meta.metrics}
            for m in source.metrics:
                if id(m) not in existing:
                    target.meta.metrics.append(m)
        if source.puid and not target.meta.puid:
            target.meta.puid = source.puid

    @staticmethod
    def _record_path(msg: SeldonMessage, state: UnitState) -> None:
        msg.meta.request_path[state.name] = state.image

    # ------------------------------------------------------------------
    # Feedback
    # ------------------------------------------------------------------
    async def send_feedback(self, feedback: Feedback) -> SeldonMessage:
        return await self._feedback(self.state.root, feedback)

    async def _feedback(self, state: UnitState, feedback: Feedback) -> SeldonMessage:
        # Deliver to this unit if it handles feedback.
        if state.has_method(UnitMethod.SEND_FEEDBACK) and state.component is not None:
            comp = state.component
            if getattr(comp, "is_async", False):
                await dispatch.send_feedback(comp, feedback, unit_id=state.name)
            else:
                result = dispatch.send_feedback(comp, feedback, unit_id=state.name)
                if inspect.isawaitable(result):
                    await result

        # Replay down the routed branch only (`PredictiveUnitBean.java:210-218`).
        if state.children:
            routing = {}
            if feedback.response is not None:
                routing = feedback.response.meta.routing
            branch = routing.get(state.name, -1)
            if branch == -1:
                if self.has_async_nodes:
                    await asyncio.gather(
                        *[self._feedback(c, feedback) for c in state.children])
                else:
                    for c in state.children:
                        await self._feedback(c, feedback)
            elif 0 <= branch < len(state.children):
                await self._feedback(state.children[branch], feedback)
            else:
                raise SeldonError(
                    f"Feedback routing for {state.name} names branch {branch} outside "
                    f"{len(state.children)} children",
                    reason="BAD_ROUTING",
                )
        return SeldonMessage()
