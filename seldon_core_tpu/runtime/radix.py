"""Radix-tree paged prefix cache: token-block trie over the global KV pool.

The PR 7 page pool made KV a pool of fixed-size pages addressed through
per-slot block tables; this module makes shared prompt prefixes a FIRST-
CLASS occupant of that pool (SGLang's RadixAttention, Zheng et al. 2024,
on vLLM's block-sharing substrate, Kwon et al. SOSP 2023). The trie is
keyed on fixed-size token blocks — one node = one token block = one page —
so a cached prefix is not an entry to copy but a path of pages to POINT AT:

- **hit = block-table entries.** ``match_and_pin`` walks the prompt's
  blocks down the trie in O(prompt blocks) and returns the pages already
  holding that prefix's KV; admission writes them into the slot's block
  row (one jitted row write) and chunk-prefills only the uncached suffix.
  No gather, no page copy — the pages are shared in place.
- **copy-on-write for partial blocks.** A prompt that runs PAST a cached
  path's full blocks but only part-way into a node's block (or repeats a
  cached sequence exactly — the match is capped at prompt-1 so the last
  token always prefills and yields first-token logits) cannot write into
  the shared page: it gets ONE fresh page plus one donated jitted page
  copy (``cow_page_copy`` — values copied, position rows past the valid
  length masked to PAD_POS so a previous occupant's run-ahead tail is
  never attended), and its writes land in the copy.
- **refcounts live in the allocator.** ``PageAllocator.alloc`` hands out
  pages at refcount 1; the trie adopts a completed slot's pages by simply
  keeping that reference, ``match_and_pin`` retains matched pages for the
  slot, and every release path is one uniform ``free`` (decrement,
  free-list on zero). A page's refcount IS the shared-ownership truth:
  refcount 1 = trie-only (evictable), >1 = some live slot's block table
  points at it (never evictable).
- **insert-in-place at completion.** ``insert`` walks the finished slot's
  prompt+generated token blocks back into the trie, transferring page
  ownership node-by-node — no dense export, no import program. Blocks the
  trie already holds free the slot's duplicate page instead (trie-path
  equality implies bit-identical KV: a block's KV depends on its whole
  token prefix, which IS the path). Only tokens whose KV is provably
  written are inserted (everything but the final credited token — its KV
  is only written when it is FED to a later step, which run-ahead may or
  may not have dispatched).
- **LRU-by-leaf eviction.** When the allocator runs dry the batcher asks
  the trie to give pages back: leaves with refcount 1 evict in
  least-recently-matched order (a parent is touched whenever a child
  matches, so parents are never younger than their children and eviction
  is deepest-coldest-first). Live-referenced pages are structurally
  excluded — eviction can shrink the cache, never corrupt a slot.

Concurrency: every public method takes ``self._lock``. Mutations come
from the batcher loop's serialized offload context (admission, insert,
evict); reads additionally come from transport threads (``stats`` at
/metrics scrape, ``match_len`` from ReplicaSet's prefix-routing probe) —
the lock is what makes the probe safe to call from anywhere. Trie methods
call allocator methods while holding the trie lock (lock order
trie -> allocator, one direction only; the allocator never calls back).
racelint models the class; tests/test_schedules.py proves the refcount
discipline under deterministic interleaving and
tests/test_radix.py hammers one hot prefix from 8 threads.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["RadixPrefixCache"]


class _Node:
    """One token block = one pool page. ``key`` is the block's tokens
    (len == page_size for full nodes, shorter for a partial tail leaf —
    only full nodes may have children, so every root-to-node path spells
    a position-aligned token prefix). ``last_match`` is a logical clock
    tick (monotonic counter, not wall time) for LRU eviction."""

    __slots__ = ("key", "page", "children", "last_match")

    def __init__(self, key: Tuple[int, ...], page: Optional[int]):
        self.key = key
        self.page = page
        # first-token -> [nodes]: siblings may share key prefixes (the
        # trie never splits nodes — a page belongs to exactly one node),
        # so lookup picks the longest-matching candidate per step
        self.children: Dict[int, List["_Node"]] = {}
        self.last_match = 0


def _common(a: Sequence[int], b: Sequence[int]) -> int:
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n


def _common_at(key: Sequence[int], ids: Sequence[int], off: int,
               limit: int) -> int:
    """Common prefix length of ``key`` and ``ids[off:limit]`` without
    materializing the slice (the match walk compares in place)."""
    n = min(len(key), limit - off)
    for i in range(n):
        if key[i] != ids[off + i]:
            return i
    return n


class RadixPrefixCache:
    """See module docstring. ``allocator`` is the batcher's PageAllocator
    (the refcount authority); ``page_size`` the tokens per block/page;
    ``bytes_per_block`` the HBM bytes one cached block's KV occupies
    (feeds the bytes-saved counter: a hit's blocks are bytes NOT copied
    and NOT recomputed)."""

    def __init__(self, allocator: Any, page_size: int,
                 bytes_per_block: int = 0):
        self._allocator = allocator
        self.page_size = int(page_size)
        self.bytes_per_block = int(bytes_per_block)
        self._lock = threading.Lock()
        self._root = _Node((), None)
        self._tick = 0
        self._blocks = 0             # nodes holding a page
        # lifetime counters (llm_stats -> metrics/registry.py
        # seldon_llm_prefix_*); mutated only under the lock
        self.hit_blocks_total = 0
        self.hit_tokens_total = 0
        self.cow_copies_total = 0
        self.evicted_blocks_total = 0
        self.bytes_saved_total = 0
        self.match_work_total = 0    # nodes visited by match walks — the
        #                              O(prompt blocks) regression signal

    # ------------------------------------------------------------------
    # lookup
    # ------------------------------------------------------------------
    def _walk(self, ids: Sequence[int], limit: int, touch: bool):
        """Shared walk: longest cached coverage of ``ids[:limit]``.
        Returns (k0, pages, cow) where ``pages`` are the full-block nodes'
        pages in path order and ``cow`` is (src_page, valid_tokens) when
        the walk ended part-way into a node's block (None otherwise);
        k0 counts full-block tokens + the cow's valid tokens."""
        ps = self.page_size
        limit = min(max(int(limit), 0), len(ids))
        node = self._root
        k0 = 0          # tokens matched == the walk's offset into ids
        pages: List[int] = []
        cow: Optional[Tuple[int, int]] = None
        work = 0
        while k0 < limit:
            # compare in place at offset k0 — slicing the remainder per
            # block would make the walk O(L^2/ps) in token copies under
            # the trie lock (this runs per routing probe, per admission)
            best, best_t = None, 0
            for cand in node.children.get(ids[k0], ()):
                work += 1
                t = _common_at(cand.key, ids, k0, limit)
                if t > best_t:
                    best, best_t = cand, t
            if best is None:
                break
            if touch:
                self._tick += 1
                best.last_match = self._tick
            if best_t == len(best.key) == ps:
                pages.append(best.page)
                k0 += ps
                node = best
                continue
            # ended inside a block (or consumed a partial tail leaf
            # whole): the page is shared and about to be written past
            # best_t — copy-on-write territory
            cow = (best.page, best_t)
            k0 += best_t
            break
        self.match_work_total += work + 1
        return k0, pages, cow

    def match_len(self, ids: Sequence[int]) -> int:
        """Cached-prefix length in TOKENS for ``ids`` — the cheap probe
        ReplicaSet's prefix-aware routing calls from transport threads.
        Read-only: no pins, no LRU touch."""
        with self._lock:
            k0, _, _ = self._walk(ids, len(ids), touch=False)
            return k0

    def match_and_pin(self, ids: Sequence[int], limit: Optional[int] = None,
                      full_blocks_only: bool = False):
        """Longest cached prefix of ``ids[:limit]``, pinned for a slot.

        Returns ``(k0, pages, cow)``: ``pages`` are the shared full-block
        pages (allocator-retained here — the caller's block table may
        point at them until it frees them), ``cow`` is (src_page,
        valid_tokens) for a partial-block continuation the caller must
        copy before writing (``full_blocks_only=True`` drops it — the
        disaggregated path shares whole blocks only), and ``k0`` is the
        total matched tokens. The cow SOURCE page is retained too: the
        caller's very next allocation may trigger eviction, and an
        unpinned source could be evicted and handed back as a fresh page
        while the pending copy still references it — the caller frees
        the cow pin once the copy is dispatched (or on its failure
        path). Callers cap ``limit`` at len(ids)-1 so at least one token
        always prefills (its logits seed the first sampled token — no
        logits storage needed in the trie)."""
        if limit is None:
            limit = len(ids)
        with self._lock:
            k0, pages, cow = self._walk(ids, limit, touch=True)
            if full_blocks_only and cow is not None:
                k0 -= cow[1]
                cow = None
            pins = pages + ([cow[0]] if cow is not None else [])
            if pins:
                self._allocator.retain(pins)
            return k0, pages, cow

    def record_hit(self, k0: int, n_shared: int, cow: bool) -> None:
        """Tally one SERVED hit. Deliberately separate from
        ``match_and_pin``: an admission can match, fail to fund its fresh
        pages, unpin, and retry every loop turn — counting at match time
        would inflate the headline reuse counters once per retry (and
        claim COW copies that were never dispatched). The batcher calls
        this exactly once, after the admission is funded."""
        with self._lock:
            self.hit_blocks_total += n_shared + (1 if cow else 0)
            self.hit_tokens_total += k0
            if cow:
                self.cow_copies_total += 1
            # full shared blocks are bytes neither copied nor recomputed;
            # a cow block is recompute saved but one page-copy paid, so it
            # does not count toward bytes saved
            self.bytes_saved_total += n_shared * self.bytes_per_block

    # ------------------------------------------------------------------
    # insertion (completion path)
    # ------------------------------------------------------------------
    def insert(self, tokens: Sequence[int], pages: Sequence[int],
               n_shared: int) -> set:
        """Walk a finished slot's token history back into the trie.

        ``tokens`` is the provably-written history (prompt + all but the
        last credited token), ``pages`` the slot's block-row pages in
        block order (shared trie pages first, then owned), ``n_shared``
        how many of them are already trie-owned. Ownership of owned pages
        transfers in place: adopted pages keep the slot's allocator
        reference (the trie's ref from now on), duplicates of blocks the
        trie already holds are freed here. Returns the set of owned page
        ids this call consumed (adopted or freed) — the caller must NOT
        free them again; everything else (shared pins, surplus tail
        pages) stays the caller's to release."""
        ps = self.page_size
        tokens = list(tokens)
        n_full = len(tokens) // ps
        tail = len(tokens) % ps
        consumed: set = set()
        with self._lock:
            node = self._root
            for i in range(n_full):
                if i >= len(pages):
                    break
                block = tuple(tokens[i * ps:(i + 1) * ps])
                page = pages[i]
                own = i >= n_shared
                node = self._insert_block(node, block, page, own, consumed)
                if node is None:
                    return consumed
            if tail and n_full < len(pages):
                self._insert_tail(node, tuple(tokens[n_full * ps:]),
                                  pages[n_full], n_full >= n_shared,
                                  consumed)
            return consumed

    def _insert_block(self, node: _Node, block: Tuple[int, ...], page: int,
                      own: bool, consumed: set) -> Optional[_Node]:
        """One full block under ``node``; returns the node to descend
        into (None aborts the walk — the path can no longer be spelled)."""
        self._tick += 1
        siblings = node.children.get(block[0], [])
        exact = next((c for c in siblings if c.key == block), None)
        if exact is not None:
            # the trie already holds this block (same path = same KV
            # bits); an owned duplicate page goes back to the pool
            if own and page != exact.page:
                self._allocator.free([page])
                consumed.add(page)
            exact.last_match = self._tick
            return exact
        if not own:
            # a shared page whose node vanished mid-flight (cannot happen
            # while pinned — defensive): stop inserting, never adopt a
            # page the slot does not own
            return None
        partial = next(
            (c for c in siblings
             if len(c.key) < len(block) and block[:len(c.key)] == c.key),
            None)
        if partial is not None and self._allocator.refs_of(partial.page) == 1:
            # upgrade the colder partial leaf in place: our page holds the
            # same leading KV plus more valid positions
            self._allocator.free([partial.page])
            self.evicted_blocks_total += 1
            self._blocks -= 1
            partial.key = block
            partial.page = page
            partial.last_match = self._tick
            consumed.add(page)
            self._blocks += 1
            return partial
        child = _Node(block, page)
        child.last_match = self._tick
        node.children.setdefault(block[0], []).append(child)
        consumed.add(page)
        self._blocks += 1
        return child

    def _insert_tail(self, node: _Node, tail: Tuple[int, ...], page: int,
                     own: bool, consumed: set) -> None:
        """The final partial block (valid tokens < page_size)."""
        if not own:
            return
        self._tick += 1
        siblings = node.children.get(tail[0], [])
        covering = next(
            (c for c in siblings
             if len(c.key) >= len(tail) and c.key[:len(tail)] == tail),
            None)
        if covering is not None:
            # an existing node already serves every lookup ours could
            self._allocator.free([page])
            consumed.add(page)
            covering.last_match = self._tick
            return
        shorter = next(
            (c for c in siblings
             if len(c.key) < len(tail) and tail[:len(c.key)] == c.key),
            None)
        if shorter is not None and self._allocator.refs_of(shorter.page) == 1:
            self._allocator.free([shorter.page])
            self.evicted_blocks_total += 1
            shorter.key = tail
            shorter.page = page
            shorter.last_match = self._tick
            consumed.add(page)
            return
        child = _Node(tail, page)
        child.last_match = self._tick
        node.children.setdefault(tail[0], []).append(child)
        consumed.add(page)
        self._blocks += 1

    # ------------------------------------------------------------------
    # eviction
    # ------------------------------------------------------------------
    def evict(self, need_free: int) -> bool:
        """Give pages back until the allocator has ``need_free`` free
        pages. Only leaves whose page refcount is 1 (trie-only — no live
        slot's block table references them) are candidates, coldest
        ``last_match`` first. Batched: one trie walk + one allocator lock
        acquisition (``refs_map``) per ROUND, evicting as many of the
        round's candidates as needed coldest-first, then re-walking only
        if interior nodes became leaves (so relief is O(depth) walks, not
        O(evicted_pages) — this runs on the admission/page-grow path
        where each extra O(nodes) lock round-trip is a serving stall).
        Returns True when the target was reached."""
        with self._lock:
            while self._allocator.free_count() < need_free:
                leaves = self._evictable_leaves()
                if not leaves:
                    return False
                leaves.sort(key=lambda pn: pn[1].last_match)
                for parent, node in leaves:
                    if self._allocator.free_count() >= need_free:
                        break
                    sibs = parent.children[node.key[0]]
                    sibs.remove(node)
                    if not sibs:
                        del parent.children[node.key[0]]
                    self._allocator.free([node.page])
                    self._blocks -= 1
                    self.evicted_blocks_total += 1
            return True

    def _iter_nodes(self):
        """(parent, node) pairs of every trie node — THE traversal,
        shared by stats/clear/eviction (callers hold the lock)."""
        stack = [(self._root, c)
                 for cs in self._root.children.values() for c in cs]
        while stack:
            parent, node = stack.pop()
            yield parent, node
            stack.extend((node, c)
                         for cs in node.children.values() for c in cs)

    def _evictable_leaves(self):
        """All (parent, leaf) pairs whose page refcount is 1 — one trie
        walk, refcounts read in one batched allocator call."""
        pairs = [(p, n) for p, n in self._iter_nodes() if not n.children]
        refs = self._allocator.refs_map([n.page for _, n in pairs])
        return [pn for pn, rc in zip(pairs, refs) if rc == 1]

    def clear(self) -> None:
        """Drop every cached block (frees the trie's page references)."""
        with self._lock:
            pages = [n.page for _, n in self._iter_nodes()]
            if pages:
                self._allocator.free(pages)
            self.evicted_blocks_total += len(pages)
            self._root = _Node((), None)
            self._blocks = 0

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """One consistent snapshot for llm_stats (counters are lifetime
        tallies — metrics/registry.py syncs them with the catch-up
        idiom). ``prefix_shared_pages`` counts cached pages some live
        slot currently references (refcount > 1). One trie walk, one
        allocator lock acquisition (``refs_map``) — this runs per
        /metrics scrape and must not serialize admissions O(nodes)
        times."""
        with self._lock:
            cached_pages = [n.page for _, n in self._iter_nodes()]
            shared = sum(
                1 for rc in self._allocator.refs_map(cached_pages)
                if rc > 1)
            return {
                "prefix_cached_blocks": self._blocks,
                "prefix_shared_pages": shared,
                "prefix_hit_blocks": self.hit_blocks_total,
                "prefix_hit_tokens": self.hit_tokens_total,
                "prefix_cow_copies": self.cow_copies_total,
                "prefix_evicted_blocks": self.evicted_blocks_total,
                "prefix_bytes_saved": self.bytes_saved_total,
            }
