"""Edge-program compiler: PredictorSpec -> native edge graph program.

The control plane compiles inference graphs whose every unit is a builtin
(the reference's in-engine hardcoded units, `engine/src/main/java/io/seldon/
engine/predictors/PredictorConfigBean.java:77-82`) into a compact JSON
program that the native edge server (native/edge.cc) executes without
touching Python — the compiled-orchestrator hot path that the reference gets
from its Java engine. Graphs with any other unit (JAX models, remote
endpoints, stateful routers) return None and are served by the Python engine
behind the edge's shared-memory-ring fallback.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
from typing import Any, Dict, List, Optional

from seldon_core_tpu.contracts.graph import (
    PredictiveUnit,
    PredictorSpec,
    UnitImplementation,
)

_NATIVE_KINDS = {
    UnitImplementation.SIMPLE_MODEL: "SIMPLE_MODEL",
    UnitImplementation.SIMPLE_ROUTER: "SIMPLE_ROUTER",
    UnitImplementation.RANDOM_ABTEST: "RANDOM_ABTEST",
    UnitImplementation.AVERAGE_COMBINER: "AVERAGE_COMBINER",
    # Stateful bandits execute natively too (per-edge-process state, the
    # multi-replica model of analytics/routers.py); seeded instances also
    # run native — the edge replays the numpy/CPython streams bit-exactly
    # (np_rng.h: PCG64 + Lemire integers + ziggurat gamma/beta).
    UnitImplementation.EPSILON_GREEDY: "EPSILON_GREEDY",
    UnitImplementation.THOMPSON_SAMPLING: "THOMPSON_SAMPLING",
}

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "native"
)
EDGE_BINARY = os.path.join(_NATIVE_DIR, "build", "seldon_edge")
LOADGEN_BINARY = os.path.join(_NATIVE_DIR, "build", "seldon_loadgen")


# Golden draws recorded from numpy 2.0.2 — the version the checked-in
# ziggurat tables (native/ziggurat_tables.h) and np_rng.h replay logic were
# extracted from and verified against. Seeded-native routing is only sound
# when the INSTALLED numpy produces these exact streams: the native edge
# replays numpy draw-for-draw, and the Python engine plane uses the installed
# numpy directly, so any drift would silently desync the two planes
# (ADVICE.md round 5). pyproject pins numpy to a known-good range; this probe
# is the belt-and-braces runtime check before enabling seeded-native compile.
_NUMPY_PARITY_SEED_BETA = 20260803
_NUMPY_PARITY_BETA = (
    ((1.0, 1.0), 0.8861055853627264),
    ((0.5, 0.5), 0.2187824033435847),
    ((2.5, 1.7), 0.6781937015134641),
    ((9.3, 0.2), 0.9919305747956653),
)
_NUMPY_PARITY_SEED_GAMMA = 7
_NUMPY_PARITY_GAMMA = (
    (0.4, 0.309950474806918),
    (1.0, 0.5685486573832514),
    (3.7, 1.982692295846162),
)
_NUMPY_PARITY_SEED_INT = 123
_NUMPY_PARITY_INTEGERS = (15, 682, 592, 53)
_NUMPY_PARITY_UNIFORM = (0.22035987277261138, 0.1843718106986697)

_numpy_parity_cache: Optional[bool] = None


def numpy_stream_parity_ok() -> bool:
    """Cheap startup probe: do the installed numpy's Generator streams
    (beta/gamma ziggurat paths, Lemire integers, uniform doubles) still match
    the numpy 2.0.2 goldens the native replay was extracted from? Bit-exact
    comparison — parity is all-or-nothing. Cached after the first call."""
    global _numpy_parity_cache
    if _numpy_parity_cache is not None:
        return _numpy_parity_cache
    import numpy as np

    ok = True
    try:
        g = np.random.Generator(np.random.PCG64(_NUMPY_PARITY_SEED_BETA))
        ok &= all(g.beta(a, b) == want for (a, b), want in _NUMPY_PARITY_BETA)
        g = np.random.Generator(np.random.PCG64(_NUMPY_PARITY_SEED_GAMMA))
        ok &= all(g.standard_gamma(shape) == want for shape, want in _NUMPY_PARITY_GAMMA)
        g = np.random.Generator(np.random.PCG64(_NUMPY_PARITY_SEED_INT))
        ok &= tuple(g.integers(0, 1000, 4).tolist()) == _NUMPY_PARITY_INTEGERS
        ok &= tuple(g.random(2).tolist()) == _NUMPY_PARITY_UNIFORM
    except Exception:
        ok = False
    if not ok:
        import logging

        logging.getLogger(__name__).warning(
            "installed numpy %s diverges from the 2.0.2 streams the native "
            "tables were extracted from; seeded units stay on the Python "
            "engine (native replay would desync)", np.__version__,
        )
    _numpy_parity_cache = bool(ok)
    return _numpy_parity_cache


def build_edge_binaries() -> bool:
    """Build the native edge/loadgens if needed; False when no toolchain."""
    binaries = (EDGE_BINARY, LOADGEN_BINARY, LOADGEN_BINARY + "_grpc")
    if all(os.path.exists(b) for b in binaries):
        src = max(
            os.path.getmtime(os.path.join(_NATIVE_DIR, f))
            for f in ("edge.cc", "ring.cc", "loadgen_http.cc", "loadgen_grpc.cc")
        )
        if min(os.path.getmtime(b) for b in binaries) >= src:
            return True
    if shutil.which("make") is None:
        return False
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR], check=True, capture_output=True)
        return True
    except subprocess.CalledProcessError:
        return False


def compile_edge_program(
    spec: PredictorSpec,
    deployment: str = "",
    predictor: str = "",
    device_components: Optional[Dict[str, Any]] = None,
) -> Optional[Dict[str, Any]]:
    """Return the native edge program for this graph, or None if any unit
    cannot execute natively (the edge then runs in ring-fallback mode).

    ``device_components`` (unit name -> live SeldonComponent) additionally
    compiles leaf MODEL units backed by real in-process models (JAXServer,
    sklearn, user components) to DEVICE_MODEL nodes: the edge executes the
    graph natively and ships only the packed tensor over the ring to the
    engine process's ModelExecutor (transport/ipc.py kind 2), which owns the
    device and micro-batches concurrent calls. Eligibility per unit: MODEL
    type, no children, a plain ``predict`` (components overriding
    ``predict_raw`` need the full SeldonMessage and fall back)."""
    units: List[Dict[str, Any]] = []
    device_models: List[str] = []

    def _device_eligible(unit: PredictiveUnit, method: str) -> Optional[Any]:
        from seldon_core_tpu.components.component import _has_impl, has_raw

        if not device_components or unit.name not in device_components:
            return None
        component = device_components[unit.name]
        if component is None or not _has_impl(component, method) \
                or has_raw(component, method):
            return None
        if _has_impl(component, "send_feedback") or has_raw(component, "send_feedback"):
            # native feedback handling is bandit-only; a component that
            # learns from feedback must keep the Python engine in the loop
            return None
        if getattr(component, "is_async", False):
            return None
        return component

    def compile_device_unit(unit: PredictiveUnit, transformed: bool) -> Optional[int]:
        from seldon_core_tpu.contracts.graph import UnitType

        if unit.type == UnitType.TRANSFORMER and len(unit.children) == 1:
            # input transformer (e.g. an outlier detector) feeding a device
            # subtree: its transformed output flows to the child as a
            # deferred ring call chain
            component = _device_eligible(unit, "transform_input")
            if component is None:
                return None
            child = compile_unit(unit.children[0], transformed=True)
            if child is None:
                return None
            units.append({
                "name": unit.name,
                "kind": "DEVICE_TRANSFORM",
                "children": [child],
                "modelId": len(device_models),
                "className": type(component).__name__,
            })
            device_models.append(unit.name)
            return len(units) - 1
        if unit.children:
            return None  # a device model's output feeding a chain stays Python
        if unit.type not in (None, UnitType.MODEL):
            return None
        component = _device_eligible(unit, "predict")
        if component is None:
            return None
        units.append({
            "name": unit.name,
            "kind": "DEVICE_MODEL",
            "children": [],
            "modelId": len(device_models),
            "className": type(component).__name__,
        })
        device_models.append(unit.name)
        return len(units) - 1

    def compile_unit(unit: PredictiveUnit, transformed: bool = False) -> Optional[int]:
        kind = _NATIVE_KINDS.get(unit.implementation)
        if kind is None:
            return compile_device_unit(unit, transformed)
        if transformed and kind in ("SIMPLE_MODEL",):
            # a stub consuming a device-transformed value would need the
            # transformed row count at eval time, which isn't known until
            # the ring call completes — keep such graphs on the Python engine
            return None
        params = unit.parameters_dict()
        if str(params.get("python_routing", "")).lower() in ("true", "1"):
            # Seeded determinism scope: each serving PLANE replays its own
            # exact stream from the seed (same per-replica model as
            # multi-worker edges / multi-replica engines). Traffic that
            # splits across planes (e.g. strData riding the ring while
            # tensors run native) therefore interleaves two streams. A
            # deployment that needs ONE globally-deterministic stream sets
            # python_routing=true on the router to pin it to the Python
            # engine — the pre-round-4 behavior.
            return None
        try:
            seed = params.get("seed")
            seed = None if seed is None else int(seed)
            if seed is not None and not 0 <= seed < 2**53:
                # negative (numpy raises) or beyond double precision (the
                # program JSON carries numbers as doubles): Python plane
                return None
        except (TypeError, ValueError):
            return None
        if seed is not None and not numpy_stream_parity_ok():
            # installed numpy drifted from the recorded 2.0.2 streams: the
            # native replay would silently desync from the Python plane, so
            # seeded units fall back to the Python engine
            return None
        if kind in ("EPSILON_GREEDY", "THOMPSON_SAMPLING"):
            # Parameters the Python constructor would reject must surface as
            # its build error, so invalid specs fall back rather than getting
            # a silently different native default. Only the params each kind
            # actually consumes are checked — the components ignore foreign
            # kwargs, and a foreign param must not cost native execution.
            try:
                n_branches = int(params.get("n_branches", 2))
                if n_branches < 1:
                    return None
                if kind == "EPSILON_GREEDY":
                    if not 0.0 <= float(params.get("epsilon", 0.1)) <= 1.0:
                        return None
                    if not 0 <= int(params.get("best_branch", 0)) < n_branches:
                        return None
                else:
                    if float(params.get("alpha", 1.0)) <= 0:
                        return None
                    if float(params.get("beta", 1.0)) <= 0:
                        return None
            except (TypeError, ValueError):
                return None
        children: List[int] = []
        for child in unit.children:
            idx = compile_unit(child, transformed=transformed)
            if idx is None:
                return None
            children.append(idx)
        out: Dict[str, Any] = {"name": unit.name, "kind": kind, "children": children}
        if kind == "RANDOM_ABTEST":
            out["ratioA"] = float(params.get("ratioA", 0.5))
            out["nBranches"] = int(params.get("n_branches", 2))
            if seed is not None:
                out["seed"] = seed
        elif kind == "EPSILON_GREEDY":
            out["nBranches"] = int(params.get("n_branches", 2))
            out["epsilon"] = float(params.get("epsilon", 0.1))
            out["bestBranch"] = int(params.get("best_branch", 0))
            if seed is not None:
                out["seed"] = seed
        elif kind == "THOMPSON_SAMPLING":
            out["nBranches"] = int(params.get("n_branches", 2))
            out["alpha"] = float(params.get("alpha", 1.0))
            out["beta"] = float(params.get("beta", 1.0))
            if seed is not None:
                # the edge replays Generator.beta draw-for-draw
                # (np_rng.h standard_gamma/beta over the extracted
                # ziggurat tables, proven by test_np_rng_gamma_beta_parity)
                out["seed"] = seed
        units.append(out)
        return len(units) - 1

    root = compile_unit(spec.graph)
    if root is None:
        return None
    program = {
        "deployment": deployment,
        "predictor": predictor or spec.name,
        "native": True,
        "units": units,
        "root": root,
    }
    if device_models:
        program["deviceModels"] = device_models
    return program


def fallback_program(spec: PredictorSpec, deployment: str = "", predictor: str = "") -> Dict[str, Any]:
    return {
        "deployment": deployment,
        "predictor": predictor or spec.name,
        "native": False,
    }


def write_program(program: Dict[str, Any], path: str) -> str:
    with open(path, "w") as f:
        json.dump(program, f)
    return path
