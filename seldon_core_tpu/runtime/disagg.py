"""Disaggregated prefill/decode serving: the host-side coordination layer.

The compute story (docs/performance.md "Disaggregated serving"): prefill is
a compute-bound burst, decode is a bandwidth-bound trickle, and running both
on one mesh slice makes every admission a latency spike for every in-flight
stream — PR 7's chunked prefill only *interleaves* the burst. Splitting the
serving mesh (parallel/mesh.py ``disaggregated_mesh``) runs admission
prefill on a **prefill slice** and the pipelined decode batch on a
**decode slice**, with the prefilled KV moved device-to-device (DistServe,
Zhong et al. OSDI 2024; Splitwise, Patel et al. ISCA 2024).

This module is the host half of that split:

- ``TransferQueue`` — the lock-guarded handoff channel between prefill
  workers and the decode batcher. A handoff is registered at admission,
  becomes READY when the worker finishes, and is consumed by the batcher
  loop — or cancelled by a shed. Every transition is atomic under one
  lock, so a handoff is delivered exactly once and its decode-side pages
  are freed exactly once even when a shed races the worker's put (the
  interleavings tests/test_schedules.py explores).
- ``PrefillWorker`` — one worker thread per prefill-slice device: it keeps
  a committed copy of the params and (paged layout) a single-sequence
  staging page pool on its device, runs the server's own compiled prefill
  programs there (``_get_prefill`` dense, ``_get_prefill_chunk`` paged —
  the SAME programs local admission compiles, so the written KV is
  bit-identical), then moves the result onto the decode device with
  ``jax.device_put`` — a direct device-to-device copy, no host round trip
  for the KV — and publishes the handoff.
- ``PrefillWorkerPool`` — M workers behind least-backlog dispatch.

The decode side (runtime/batcher.py ``disaggregation="remote_prefill"``)
imports a ready handoff into its slot pool with one donated jitted scatter
(``ContinuousBatcher._get_handoff_import``; dense handoffs reuse the
existing ``insert``), pinned by the ``disagg.import_pages`` hlolint
contract: zero infeed/outfeed, donation aliasing intact, bytes within the
committed budget.
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)

DISAGGREGATION_MODES = ("off", "remote_prefill")

# How a finished prefill's KV reaches the decode slice: "device" is the
# direct jax.device_put fast path (shared topology); "network" frames the
# page bucket header+raw and streams it over a socket to the decode host's
# HandoffReceiver (no shared topology — ROADMAP multi-host decode).
HANDOFF_TRANSPORTS = ("device", "network")

# Outer wire format of one network handoff: an 8-byte little-endian length
# prefix, then that many frame bytes (codec/framing.py layout). The length
# is bounded before ANY allocation — a corrupt prefix must not let the
# receiver allocate attacker-controlled gigabytes.
MAX_HANDOFF_FRAME_BYTES = 1 << 33  # 8 GiB: > any pow2 bucket we ship

# TransferQueue record states (values are only compared for identity)
_STAGED = "staged"        # registered; the worker has not finished yet
_READY = "ready"          # handoff published, waiting for the batcher
_CANCELLED = "cancelled"  # shed before the worker finished


def normalize_disaggregation(value) -> str:
    """Canonical disaggregation mode ("off" or "remote_prefill"); raises
    ValueError on anything else so misconfiguration fails at load() time,
    not inside the batcher's admission path."""
    v = str(value or "off").strip().lower()
    if v in ("off", "none", "no", "0", ""):
        return "off"
    if v in ("remote_prefill", "remote-prefill", "prefill", "disagg",
             "disaggregated"):
        return "remote_prefill"
    raise ValueError(
        f"unknown disaggregation {value!r}: expected one of "
        f"{DISAGGREGATION_MODES}")


class PrefillRequest:
    """What a worker needs to prefill one admission: the (already
    truncated) prompt, its dense prefill bucket, and the page count the
    decode side allocated for it (paged layout). ``record_events`` asks
    the worker to stamp flight-recorder stage events into the Handoff
    (set when the decode side's recorder is running).

    Prefix reuse (radix trie, runtime/radix.py): when the decode side
    already caches the prompt's leading ``prefix_len`` tokens
    (``prefix_pages`` whole blocks), ``prefix_staged`` carries their KV
    as an exported page bucket — the worker imports it into its staging
    pool and computes ONLY positions ``prefix_len..``, then hands back
    only the suffix pages. The prefix ships forward as a D2D copy (bytes,
    not FLOPs); the prefill compute saved is the point."""

    __slots__ = ("job_id", "ids", "plen", "n_pages", "record_events",
                 "prefix_len", "prefix_pages", "prefix_staged")

    def __init__(self, job_id: int, ids: List[int], plen: int,
                 n_pages: int = 0, record_events: bool = False,
                 prefix_len: int = 0, prefix_pages: int = 0,
                 prefix_staged: Any = None):
        self.job_id = job_id
        self.ids = list(ids)
        self.plen = int(plen)
        self.n_pages = int(n_pages)
        self.record_events = bool(record_events)
        self.prefix_len = int(prefix_len)
        self.prefix_pages = int(prefix_pages)
        self.prefix_staged = prefix_staged


class Handoff:
    """One finished prefill, published by a worker: the staged KV already
    resident on the DECODE device (``jax.device_put`` moved it
    device-to-device; the host never materialized it), the last-position
    logits the first sampled token draws from (a small [vocab] host array
    — admission-time, once per request), and timing/bytes for the
    handoff metrics. ``error`` carries a worker-side failure instead of
    a payload — the batcher resolves the request with it.

    ``events`` carries the worker's flight-recorder stage stamps
    ((perf_counter t, kind, fields) tuples — runtime/flight.py): written by
    the WORKER thread before ``put`` publishes the handoff, read by the
    batcher after ``pop`` — ownership transfers through the TransferQueue's
    lock, so the single-writer-per-slot ring discipline holds without the
    worker ever touching a slot ring."""

    __slots__ = ("job_id", "staged", "first_logits", "error", "prefill_s",
                 "transfer_bytes", "events")

    def __init__(self, job_id: int, staged: Any = None,
                 first_logits: Optional[np.ndarray] = None,
                 error: Optional[BaseException] = None,
                 prefill_s: float = 0.0, transfer_bytes: int = 0,
                 events: Optional[list] = None):
        self.job_id = job_id
        self.staged = staged
        self.first_logits = first_logits
        self.error = error
        self.prefill_s = prefill_s
        self.transfer_bytes = transfer_bytes
        self.events = events or []


class TransferQueue:
    """Lock-guarded handoff channel between prefill workers and the decode
    batcher, with exactly-once delivery/cancellation semantics.

    Protocol (all transitions atomic under ``self._lock``):

    - ``register(job_id)`` (batcher, at admission): the job exists, STAGED.
    - ``put(handoff)`` (worker thread): STAGED -> READY, or returns False
      when the job was cancelled meanwhile — the worker just drops the
      payload (the decode-side pages were freed by the canceller).
    - ``pop()`` (batcher loop): oldest READY handoff, removed — the
      batcher now owns the import and the slot owns the pages.
    - ``cancel(job_id)`` (batcher shed paths): READY -> returns the
      handoff (the CALLER frees the pages, exactly once); STAGED ->
      marked cancelled and returns None (the caller frees the pages NOW;
      the worker's later put is refused). Unknown/already-popped ->
      None and the caller must NOT free (the slot owns them).

    An unlocked reconstruction of this state machine double-delivers a
    handoff (pop vs pop) or frees pages twice (pop vs cancel) under
    interleavings the deterministic-schedule suite finds
    (tests/test_schedules.py); the real class survives the same
    exploration."""

    def __init__(self):
        self._lock = threading.Lock()
        self._state: Dict[int, str] = {}
        self._ready: deque = deque()  # Handoff records, arrival order
        self.handoffs_total = 0
        self.transfer_bytes_total = 0
        # optional ready-notification hook (the batcher points this at a
        # loop-threadsafe wakeup); read under the lock, invoked outside it
        # so the callback can never deadlock against queue users
        self.on_ready: Optional[Any] = None

    def register(self, job_id: int) -> None:
        with self._lock:
            self._state[job_id] = _STAGED

    def put(self, handoff: Handoff) -> bool:
        """Publish a finished prefill. False = the job was cancelled while
        the worker ran (payload dropped — the canceller already freed the
        decode-side pages), OR the job is unknown / already READY. Only a
        STAGED job can become READY: with the network transport a frame
        replayed over a reconnected socket must not double-deliver."""
        with self._lock:
            st = self._state.get(handoff.job_id)
            if st is not _STAGED:
                if st is _CANCELLED:
                    del self._state[handoff.job_id]
                return False
            self._state[handoff.job_id] = _READY
            self._ready.append(handoff)
            self.handoffs_total += 1
            self.transfer_bytes_total += int(handoff.transfer_bytes)
            cb = self.on_ready
        if cb is not None:
            try:
                cb()
            except Exception:  # a wakeup hook must never kill a worker
                logger.exception("transfer-queue on_ready hook failed")
        return True

    def pop(self) -> Optional[Handoff]:
        """Oldest READY handoff, or None. The caller owns the import; the
        job's pages now belong to its slot."""
        with self._lock:
            if not self._ready:
                return None
            h = self._ready.popleft()
            self._state.pop(h.job_id, None)
            return h

    def cancel(self, job_id: int) -> Optional[Handoff]:
        """Shed a job. Returns the handoff if it was READY (caller frees
        its decode-side pages); None if it was still STAGED (caller frees
        the pages now — the worker's put will be refused) or already
        popped (caller must NOT free: the slot owns them)."""
        with self._lock:
            st = self._state.get(job_id)
            if st is _READY:
                found = None
                for i, h in enumerate(self._ready):
                    if h.job_id == job_id:
                        found = h
                        del self._ready[i]
                        break
                del self._state[job_id]
                return found
            if st is _STAGED:
                self._state[job_id] = _CANCELLED
            return None

    def ready_depth(self) -> int:
        with self._lock:
            return len(self._ready)

    def depth(self) -> int:
        """Jobs registered and not yet consumed (staged + ready)."""
        with self._lock:
            return len(self._state)

    def stats(self):
        """(handoffs_total, transfer_bytes_total, staged+ready depth) —
        one consistent snapshot for the /metrics scrape."""
        with self._lock:
            return (self.handoffs_total, self.transfer_bytes_total,
                    len(self._state))


class PrefillWorker:
    """One prefill-slice worker: a dedicated thread that runs the server's
    compiled prefill programs on its own device and hands the written KV
    to the decode device.

    The worker keeps a committed copy of the params on its device
    (``LLMServer._params_on``) and, under the paged layout, a
    single-sequence staging page pool (``RESERVED_PAGES + n_pages`` pages
    — pages 2.. back the sequence; the batcher's block-row width is
    reused so the chunk program has the batcher's exact shape contract).
    Prefill itself is the SAME compiled program local admission runs
    (``_get_prefill`` / ``_get_prefill_chunk``), just dispatched on the
    prefill device — which is what makes remote-prefill serving
    bit-exact against single-slice serving (tests/test_disagg.py).

    All cross-thread state (the backlog, the closing flag) lives under
    ``self._cond``; the staging pool and params copy are touched only by
    the worker thread after ``__init__``."""

    def __init__(self, server: Any, queue: TransferQueue, device: Any,
                 decode_device: Any, *, layout: str, max_len: int,
                 page_size: int = 0, n_pages: int = 0,
                 prefill_chunk: int = 0, name: str = "prefill-worker",
                 transport: str = "device",
                 receiver_addr: Optional[tuple] = None):
        if transport not in HANDOFF_TRANSPORTS:
            raise ValueError(
                f"unknown handoff transport {transport!r}: expected one of "
                f"{HANDOFF_TRANSPORTS}")
        if transport == "network" and receiver_addr is None:
            raise ValueError("network handoff transport needs the decode "
                             "side's HandoffReceiver address")
        self.server = server
        self.queue = queue
        self.device = device
        self.decode_device = decode_device
        self.layout = layout
        self.max_len = int(max_len)
        self.page_size = int(page_size)
        self.n_pages = int(n_pages)
        self.prefill_chunk = int(prefill_chunk)
        self.name = name
        self.transport = transport
        self.receiver_addr = receiver_addr
        self._sock = None  # persistent frame socket, worker thread only
        self._cond = threading.Condition()
        self._backlog: deque = deque()
        self._closing = False
        self._params = None    # committed copy, built on first job
        self._staging = None   # paged staging pool, built on first job
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._thread.start()

    # -- caller side ---------------------------------------------------
    def submit(self, req: PrefillRequest) -> None:
        with self._cond:
            if self._closing:
                raise RuntimeError(f"{self.name} is closed")
            self._backlog.append(req)
            self._cond.notify()

    def backlog_depth(self) -> int:
        with self._cond:
            return len(self._backlog)

    def close(self, timeout_s: float = 30.0) -> None:
        with self._cond:
            self._closing = True
            self._cond.notify_all()
        # bounded: a wedged device dispatch must not hang server shutdown
        self._thread.join(timeout=timeout_s)
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    # -- worker side ---------------------------------------------------
    def _next_job(self) -> Optional[PrefillRequest]:
        with self._cond:
            while not self._backlog and not self._closing:
                self._cond.wait(timeout=0.5)
            if self._backlog:
                return self._backlog.popleft()
            return None  # closing and drained

    def _run(self) -> None:
        while True:
            req = self._next_job()
            if req is None:
                return
            try:
                handoff = self._prefill_one(req)
            except BaseException as e:  # noqa: BLE001 — worker must not die
                logger.exception("prefill worker %s failed job %d",
                                 self.name, req.job_id)
                handoff = Handoff(req.job_id, error=e)
            self._publish(handoff)

    def _ensure_state(self):
        import jax

        if self._params is None:
            self._params = self.server._params_on(self.device)
        if self.layout == "paged" and self._staging is None:
            from seldon_core_tpu.models.transformer import RESERVED_PAGES

            # server-cached compile: M workers share one staging-init
            # program; each executes it once onto its own device
            pool = self.server._get_staging_pool_init(
                RESERVED_PAGES + self.n_pages, self.page_size)()
            self._staging = jax.device_put(pool, self.device)

    def _prefill_one(self, req: PrefillRequest) -> Handoff:
        import time

        t0 = time.perf_counter()
        self._ensure_state()
        if self.layout == "paged":
            staged, first_logits = self._prefill_paged(req)
        else:
            staged, first_logits = self._prefill_dense(req)
        import jax

        t1 = time.perf_counter()
        from seldon_core_tpu.runtime.flight import (
            EV_HANDOFF_COMPUTE, EV_HANDOFF_TRANSFER)

        if self.transport == "network":
            # cross-host: no shared topology for a device-to-device put.
            # The KV stays on the prefill device here; ``_frame_handoff``
            # pulls it to host in ONE bulk transfer and ships it as a
            # frame. The transfer event is stamped by the RECEIVER (it
            # owns the wire-bytes count and the decode-side import time).
            events = []
            if req.record_events:
                events = [(t1, EV_HANDOFF_COMPUTE,
                           {"worker": self.name, "dur_s": t1 - t0})]
            return Handoff(req.job_id, staged=staged,
                           first_logits=first_logits, prefill_s=t1 - t0,
                           events=events)
        # THE handoff: a direct device-to-device copy onto the decode
        # slice — the KV never rounds through host memory (the jitted
        # decode-side import is hlolint-checked for zero infeed/outfeed)
        moved = jax.device_put(staged, self.decode_device)
        nbytes = sum(int(getattr(leaf, "nbytes", 0))
                     for leaf in jax.tree.leaves(moved))
        t2 = time.perf_counter()
        events = []
        if req.record_events:
            events = [
                (t1, EV_HANDOFF_COMPUTE,
                 {"worker": self.name, "dur_s": t1 - t0}),
                (t2, EV_HANDOFF_TRANSFER,
                 {"bytes": nbytes, "dur_s": t2 - t1}),
            ]
        return Handoff(req.job_id, staged=moved, first_logits=first_logits,
                       prefill_s=t2 - t0,
                       transfer_bytes=nbytes, events=events)

    # -- network transport (worker side) -------------------------------
    def _publish(self, handoff: Handoff) -> None:
        """Deliver a finished handoff. Device transport (and every error
        handoff) goes straight into the TransferQueue; network transport
        frames the staged KV and streams it to the decode host's
        ``HandoffReceiver``, which runs the SAME ``queue.put`` there — so
        the exactly-once staged/cancel protocol is identical on both
        transports."""
        if self.transport != "network" or handoff.error is not None:
            self.queue.put(handoff)
            return
        try:
            import jax

            # the worker thread pays this wait either way (the encoder's
            # bulk device_get blocks on the async prefill values); taking
            # it BEFORE the codec keeps seldon_frame_encode_seconds a
            # serialization number instead of a compute-tail number; the
            # decode side never waits here — this is the worker's thread
            jax.block_until_ready(handoff.staged)
            payload = self._frame_handoff(handoff)
            self._send_frame(payload)
        except BaseException as e:  # noqa: BLE001 — worker must not die
            logger.exception("prefill worker %s could not ship job %d over "
                             "the network handoff", self.name,
                             handoff.job_id)
            self.queue.put(Handoff(handoff.job_id, error=e))

    def _frame_handoff(self, handoff: Handoff) -> bytes:
        """Serialize one handoff as a frame: tree skeleton + job metadata
        in the JSON section, KV pages and first-token logits as raw
        tensor buffers. ``encode_frame`` pulls every device leaf to host
        in one bulk ``jax.device_get`` — the framing contract graftlint
        enforces on this path."""
        from seldon_core_tpu.codec import framing

        skel, leaves = framing.tree_skeleton(handoff.staged)
        tensors = list(leaves)
        fl_ref = None
        if handoff.first_logits is not None:
            fl_ref = len(tensors)
            tensors.append(handoff.first_logits)
        meta = {
            "kind": "KVHandoff",
            "job_id": handoff.job_id,
            "prefill_s": handoff.prefill_s,
            "skeleton": skel,
            "first_logits_ref": fl_ref,
            "record_events": bool(handoff.events),
            "events": [[t, kind, fields]
                       for (t, kind, fields) in handoff.events],
        }
        return framing.encode_frame(meta, tensors, path="handoff")

    def _send_frame(self, payload: bytes) -> None:
        """Ship one length-prefixed frame over the persistent socket,
        reconnecting once on a broken pipe (the receiver tolerates
        reconnects; the TransferQueue refuses replayed job_ids)."""
        import socket
        import struct

        wire = struct.pack("<Q", len(payload)) + payload
        for attempt in (0, 1):
            try:
                if self._sock is None:
                    self._sock = socket.create_connection(
                        self.receiver_addr, timeout=30.0)
                    self._sock.setsockopt(socket.IPPROTO_TCP,
                                          socket.TCP_NODELAY, 1)
                self._sock.sendall(wire)
                return
            except OSError:
                if self._sock is not None:
                    try:
                        self._sock.close()
                    finally:
                        self._sock = None
                if attempt:
                    raise

    def _prefill_dense(self, req: PrefillRequest):
        """One-shot dense prefill at the request's bucket — the same
        compiled program (and therefore the same KV bits) as the local
        dense admission path (``ContinuousBatcher._admit``)."""
        import jax.numpy as jnp

        from seldon_core_tpu.models.transformer import PAD_POS

        L = len(req.ids)
        toks = np.zeros((1, req.plen), np.int32)
        pos = np.full((1, req.plen), PAD_POS, np.int32)
        toks[0, :L] = req.ids
        pos[0, :L] = np.arange(L)
        fn = self.server._get_prefill(1, req.plen, self.max_len)
        logits, cache1 = fn(self._params, jnp.asarray(toks),
                            jnp.asarray(pos))
        # graftlint: allow-host-sync-in-hot-path(admission-time sync on the PREFILL worker thread, once per request: the first sampled token's logits must reach the host; the decode slice never blocks on it)
        first_logits = np.asarray(logits[0, L - 1]).astype(np.float32)
        return cache1, first_logits

    def _prefill_paged(self, req: PrefillRequest):
        """Chunked prefill into the staging pool through a staging block
        row — the same compiled chunk program type as local paged
        admission (``_prefill_step``), on the prefill device. The staging
        pool is reused across jobs: its pages are position-reset before
        each prompt so no previous occupant's positions survive.

        Prefix reuse: when the request carries a decode-side radix hit
        (``prefix_pages`` exported blocks), the bucket imports into the
        staging pool's leading sequence pages and the chunk loop starts
        at ``prefix_len`` — the suffix chunks ATTEND over the imported
        prefix through the same staging row, so the written suffix KV is
        bit-identical to a cold full prefill, at suffix-only FLOPs."""
        import jax
        import jax.numpy as jnp

        from seldon_core_tpu.models.transformer import (
            NULL_PAGE, PAD_POS, RESERVED_PAGES, TRASH_PAGE)
        from seldon_core_tpu.runtime.batcher import _page_table_ops

        reset_pages = _page_table_ops()[2]
        n0 = req.n_pages or -(-len(req.ids) // self.page_size)
        n_pre = min(req.prefix_pages, n0) if req.prefix_staged is not None \
            else 0
        ids_np = np.full((self.n_pages,), TRASH_PAGE, np.int32)
        ids_np[:n0] = np.arange(RESERVED_PAGES, RESERVED_PAGES + n0)
        self._staging = reset_pages(self._staging, jnp.asarray(ids_np))
        row = np.full((self.n_pages,), NULL_PAGE, np.int32)
        row[:n0] = np.arange(RESERVED_PAGES, RESERVED_PAGES + n0)
        bt_row = jnp.asarray(row[None, :])
        if n_pre:
            # decode-side cached prefix: D2D the exported bucket onto this
            # device and scatter it into the sequence's leading staging
            # pages (the same jitted import program the decode side runs)
            bucket = jax.device_put(req.prefix_staged, self.device)
            staged_pages = (jax.tree.leaves(bucket)[0].shape[0]
                            - RESERVED_PAGES)
            imp = self.server._get_handoff_import(self.n_pages, staged_pages)
            pre_row = np.full((self.n_pages,), NULL_PAGE, np.int32)
            pre_row[:n_pre] = np.arange(RESERVED_PAGES,
                                        RESERVED_PAGES + n_pre)
            self._staging = imp(self._staging, bucket, jnp.asarray(pre_row),
                                jnp.asarray(n_pre, jnp.int32))

        C = min(self.prefill_chunk, req.plen) or req.plen
        fn = self.server._get_prefill_chunk(C, self.n_pages)
        L = len(req.ids)
        logits = None
        n = 0
        start = n_pre * self.page_size if n_pre else 0
        while start < L:
            part = req.ids[start:start + C]
            n = len(part)
            toks = np.zeros((1, C), np.int32)
            pos = np.full((1, C), PAD_POS, np.int32)
            toks[0, :n] = part
            pos[0, :n] = np.arange(start, start + n)
            logits, self._staging = fn(self._params, self._staging, bt_row,
                                       jnp.asarray(toks), jnp.asarray(pos))
            start += n
        # graftlint: allow-host-sync-in-hot-path(admission-time sync on the PREFILL worker thread, once per request: the LAST chunk's logits seed the first sampled token; the decode slice never blocks on it)
        first_logits = np.asarray(logits[0, n - 1]).astype(np.float32)
        # Ship only a power-of-two page bucket covering the pages THIS
        # worker wrote (the suffix — imported prefix pages never travel
        # back: the decode side still holds their originals), not the
        # whole max_len staging pool: interconnect bytes track the
        # uncached suffix length (DECODE_NOTES.md "interconnect math")
        # and the decode-side import stays at O(log n_pages) compiles.
        # The slice runs on the prefill device; the import masks rows
        # past the valid count to TRASH_PAGE so bucket padding never
        # lands in a live page.
        from seldon_core_tpu.runtime.batcher import pow2_bucket

        n_suffix = n0 - n_pre
        b = pow2_bucket(n_suffix, self.n_pages - n_pre)
        staged = jax.tree.map(
            lambda p: p[n_pre:n_pre + RESERVED_PAGES + b], self._staging)
        return staged, first_logits


class TruncatedStream(ConnectionError):
    """Mid-message EOF. Carries the bytes read so far: the frame layout
    puts the metadata section (and so the job_id) ahead of the tensor
    payload, so the receiver can usually still resolve the victim job
    with an error handoff instead of leaking its staged decode-side
    slot (the PR 19 leak sweep's truncated-frame finding)."""

    def __init__(self, msg: str, partial: bytes = b""):
        super().__init__(msg)
        self.partial = partial


# Bounded read for frames the receiver refuses to take fully (declared
# length over MAX_HANDOFF_FRAME_BYTES): enough for header + tensor table
# + metadata JSON on any real handoff, so the job_id is recoverable
# without trusting the hostile length prefix.
HANDOFF_META_PROBE_BYTES = 1 << 20


def _recv_exact(conn, n: int) -> Optional[bytes]:
    """Read exactly ``n`` bytes from a socket, or None on clean EOF.
    A mid-message EOF raises — a half-frame must never decode."""
    buf = bytearray()
    while len(buf) < n:
        chunk = conn.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            if buf:
                raise TruncatedStream(
                    f"handoff stream truncated: wanted {n} bytes, "
                    f"got {len(buf)}", partial=bytes(buf))
            return None
        buf.extend(chunk)
    return bytes(buf)


class HandoffReceiver:
    """Decode-host side of the network KV handoff: a TCP listener whose
    reader threads decode incoming frames, land the KV on the decode
    device with one ``jax.device_put``, and publish through the SAME
    ``TransferQueue.put`` the device transport uses — cancel/shed and
    exactly-once semantics are transport-independent by construction.

    A malformed frame never kills the receiver: the frame layout puts
    the metadata section before the payload, so a corrupt tensor region
    still yields the ``job_id`` (``decode_frame(meta_only=True)``) and
    the job is resolved with an error handoff — one request fails, the
    batch survives (the chaos-harness poison contract). A frame whose
    metadata is unreadable is logged and dropped; the outer length
    prefix is bounds-checked before ANY allocation."""

    def __init__(self, queue: TransferQueue, device: Any,
                 host: str = "127.0.0.1"):
        import socket

        self.queue = queue
        self.device = device
        self._lock = threading.Lock()
        self.network_bytes_total = 0  # wire payload bytes, under _lock
        self._closing = False
        self._conns: List[Any] = []
        self._threads: List[threading.Thread] = []
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.bind((host, 0))
        self._listener.listen(16)
        self.addr = self._listener.getsockname()
        t = threading.Thread(target=self._accept_loop,
                             name="handoff-receiver", daemon=True)
        self._threads.append(t)
        t.start()

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed by close()
            with self._lock:
                if self._closing:
                    conn.close()
                    return
                self._conns.append(conn)
                t = threading.Thread(target=self._read_loop, args=(conn,),
                                     name="handoff-reader", daemon=True)
                self._threads.append(t)
            t.start()

    def _read_loop(self, conn) -> None:
        import struct

        try:
            while True:
                head = _recv_exact(conn, 8)
                if head is None:
                    return
                (n,) = struct.unpack("<Q", head)
                if n > MAX_HANDOFF_FRAME_BYTES:
                    # refusing the frame must not leak the job: read a
                    # BOUNDED probe (never the hostile declared length) —
                    # the leading metadata section usually survives, and
                    # resolving the job with an error handoff frees its
                    # staged decode-side slot instead of hanging it
                    probe = b""
                    try:
                        probe = _recv_exact(
                            conn, min(n, HANDOFF_META_PROBE_BYTES)) or b""
                    except TruncatedStream as te:
                        probe = te.partial
                    except (OSError, ConnectionError):
                        pass
                    self._refuse(
                        probe,
                        f"frame declares {n} bytes "
                        f"(cap {MAX_HANDOFF_FRAME_BYTES})")
                    return
                try:
                    payload = _recv_exact(conn, n)
                except TruncatedStream as te:
                    self._refuse(te.partial, str(te))
                    return
                if payload is None:
                    return
                handoff = self._materialize(payload)
                if handoff is not None:
                    self.queue.put(handoff)
        except (OSError, ConnectionError) as e:
            with self._lock:
                closing = self._closing
            if not closing:
                logger.warning("handoff connection dropped: %s", e)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _materialize(self, payload: bytes) -> Optional[Handoff]:
        """One received frame -> one Handoff with the KV resident on the
        decode device. Decode failures become error handoffs when the
        metadata (and so the job_id) survives, else None (drop)."""
        import time

        import jax

        from seldon_core_tpu.codec import framing
        from seldon_core_tpu.runtime.flight import EV_HANDOFF_TRANSFER

        t0 = time.perf_counter()
        try:
            meta, tensors = framing.decode_frame(payload, path="handoff")
            if meta.get("kind") != "KVHandoff":
                raise framing.FrameError(
                    f"expected a KVHandoff frame, got {meta.get('kind')!r}")
            skel = meta["skeleton"]
            fl_ref = meta.get("first_logits_ref")
            first_logits = None
            if fl_ref is not None:
                # .copy() releases the frame buffer once the tree's leaves
                # are device-resident — the [vocab] logits are the only
                # host-side survivor of the payload
                first_logits = tensors[fl_ref].copy()
            staged = framing.tree_unskeleton(skel, tensors)
            staged = jax.device_put(staged, self.device)
            t1 = time.perf_counter()
            events = [(e[0], e[1], e[2]) for e in meta.get("events", ())]
            if meta.get("record_events"):
                events.append((t1, EV_HANDOFF_TRANSFER,
                               {"bytes": len(payload), "dur_s": t1 - t0}))
            with self._lock:
                self.network_bytes_total += len(payload)
            return Handoff(meta["job_id"], staged=staged,
                           first_logits=first_logits,
                           prefill_s=meta.get("prefill_s", 0.0),
                           transfer_bytes=len(payload), events=events)
        except Exception as e:  # noqa: BLE001 — receiver must not die
            job_id = None
            try:
                meta, _ = framing.decode_frame(payload, meta_only=True,
                                               path="handoff")
                job_id = meta.get("job_id")
            except Exception:  # noqa: BLE001
                pass
            if job_id is None:
                logger.exception("dropping undecodable handoff frame "
                                 "(no recoverable job_id)")
                return None
            logger.exception("handoff frame for job %s failed to decode; "
                             "resolving with error", job_id)
            return Handoff(job_id, error=e)

    def _refuse(self, prefix: bytes, why: str) -> None:
        """Last-ditch resolution for a frame the receiver will never
        fully read (oversized declared length, mid-frame truncation).
        The metadata section leads the frame, so the prefix usually
        still decodes with ``meta_only=True`` — publishing an error
        handoff then releases the job's staged decode-side slot (pages,
        prefix pins, the client future) through the same exactly-once
        queue path a poisoned-but-complete frame takes. Without a
        recoverable job_id the frame is logged and dropped: the slot
        leak is then the sender's bug to surface, not silently ours."""
        from seldon_core_tpu.codec import framing

        job_id = None
        try:
            meta, _ = framing.decode_frame(prefix, meta_only=True,
                                           path="handoff")
            if meta.get("kind") == "KVHandoff":
                job_id = meta.get("job_id")
        except Exception:  # noqa: BLE001 — the prefix is hostile input
            pass
        if job_id is None:
            logger.error("dropping unresolvable handoff frame (%s; "
                         "no recoverable job_id in %d probe bytes)",
                         why, len(prefix))
            return
        logger.error("handoff frame for job %s refused (%s); "
                     "resolving with error", job_id, why)
        self.queue.put(Handoff(job_id, error=ConnectionError(why)))

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"handoff_network_bytes_total": self.network_bytes_total}

    def close(self, timeout_s: float = 5.0) -> None:
        import socket

        with self._lock:
            self._closing = True
            conns = list(self._conns)
        for c in conns:
            # close() from another thread does not interrupt a blocked
            # recv(); shutdown() does — the reader sees EOF and exits
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        # likewise a blocked accept() survives listener.close(); a
        # zero-byte self-connect wakes it so it can observe _closing
        try:
            with socket.create_connection(self.addr, timeout=1.0):
                pass
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=timeout_s)


class PrefillWorkerPool:
    """M prefill workers behind least-backlog dispatch, publishing into
    one shared TransferQueue. One worker per prefill-slice device is the
    natural shape (each worker's programs are committed to its device);
    more devices than workers just leaves slices idle."""

    def __init__(self, server: Any, devices: Sequence, decode_device: Any,
                 *, layout: str, max_len: int, page_size: int = 0,
                 n_pages: int = 0, prefill_chunk: int = 0,
                 queue: Optional[TransferQueue] = None,
                 transport: str = "device",
                 receiver_addr: Optional[tuple] = None):
        # ``queue``: adopt an EXISTING TransferQueue instead of creating
        # one — the disagg-rebalance actuator builds the replacement pool
        # on the batcher's live queue so jobs staged on the outgoing pool
        # keep their exactly-once delivery path (runtime/batcher.py
        # ``rebalance_disagg``).
        self.queue = queue if queue is not None else TransferQueue()
        self.transport = transport
        self.receiver_addr = receiver_addr
        self.workers = [
            PrefillWorker(server, self.queue, dev, decode_device,
                          layout=layout, max_len=max_len,
                          page_size=page_size, n_pages=n_pages,
                          prefill_chunk=prefill_chunk,
                          name=f"prefill-worker-{i}",
                          transport=transport, receiver_addr=receiver_addr)
            for i, dev in enumerate(devices)
        ]

    def submit(self, req: PrefillRequest) -> None:
        self.queue.register(req.job_id)
        # least-backlog, lowest index breaks ties: deterministic placement
        # keeps parity tests and schedule replays reproducible
        _, w = min(enumerate(self.workers),
                   key=lambda iw: (iw[1].backlog_depth(), iw[0]))
        w.submit(req)

    def backlog_depth(self) -> int:
        return sum(w.backlog_depth() for w in self.workers)

    def close(self, timeout_s: float = 30.0) -> None:
        for w in self.workers:
            w.close(timeout_s=timeout_s)
