"""Batched LoRA adapter registry: many tenants' low-rank deltas in one
dense HBM pool, applied inside the shared decode/prefill/verify programs.

The multi-tenant story (ROADMAP item 5, the reference platform's
multi-model graphs made TPU-native): hundreds of tenants share one set of
base weights, each bringing a small low-rank adapter (LoRA; Hu et al.
2021), and heterogeneous tenants ride ONE continuous batch at near-base
throughput — the S-LoRA / Punica design (Sheng et al. 2023, Chen et al.
2023): adapters live in a dense ``[n_adapters, ...]`` pool, each batch
slot carries an ``adapter_id``, and the compiled step gathers the slot's
A/B factors and adds ``(x @ A) @ B * scale`` per adapted projection — one
extra gather+einsum pair, no per-tenant program, no recompilation when
tenants come and go.

Design points:

- **adapter_id 0 is the reserved identity.** Row 0 of every pool factor
  is zeros and its scale is 0, so untenanted traffic runs THE SAME
  compiled program with a provably-zero delta (``x @ 0 = 0`` exactly, and
  ``q + 0 == q`` bitwise — identity-adapter slots are bit-exact against
  the unadapted program; tests/test_adapters.py pins it). One program
  shape serves base and adapted traffic alike.
- **q / o / FFN projections only — never K/V.** The K and V projection
  weights stay base-model weights for every tenant, so the per-layer KV
  computation from a given hidden state is identical across tenants and
  the paged pool holds every tenant's cache in one shape/dtype. Loading
  an adapter that carries k/v factors raises ValueError at load time —
  adapting K/V would fork the KV-cache semantics per tenant (see
  docs/multitenancy.md "The KV-purity invariant" for what this does and
  does not buy: hidden states downstream of an adapted projection still
  embed the delta, so the radix prefix trie serves BASE-adapter traffic
  only; adapted admissions skip trie match/insert).
- **load/evict through the storage layer, refcounted like pool pages.**
  ``load_uri`` fetches ``adapter.json`` + ``weights.npz`` via
  seldon_core_tpu.storage (gs://, s3://, file://...); ``load`` takes
  in-memory factors. A live batcher slot ``pin``s its adapter at
  admission and ``unpin``s at release, and ``evict`` refuses while the
  refcount is nonzero — the pool can never drop an adapter a live slot's
  next dispatch would gather (the PR 7/12 page-refcount invariant, proven
  under deterministic interleaving in tests/test_schedules.py).
- **pool writes are NOT donated.** Loading swaps in fresh pool arrays
  (functional ``.at[row].set``) under the lock instead of donating the
  old buffers: a dispatch that read the old pool reference microseconds
  earlier still holds valid arrays, so adapter management can never
  invalidate an in-flight step. Loads are control-plane-rate events; the
  one-row copy is noise next to that safety.

Concurrency: every public method takes ``self._lock``. Loads/evicts come
from management calls on transport threads, pins/unpins from the batcher
loop's offload context, ``pool()`` from every dispatch, and ``stats()``
from /metrics scrape threads. racelint models the class
(tests/test_racelint.py fixture pair) and tests/test_schedules.py proves
the unlocked reconstruction loses updates while the real registry
survives opcode exploration.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

__all__ = ["AdapterRegistry", "ADAPTED_PROJECTIONS", "FORBIDDEN_PROJECTIONS",
           "IDENTITY_ADAPTER_ID", "DEFAULT_LORA_RANK"]

# The adapted projections, by base-weight name: attention q and o plus the
# three SwiGLU FFN mats. K and V are deliberately absent — the KV-purity
# invariant above; load() rejects factors for them by name.
ADAPTED_PROJECTIONS = ("wq", "wo", "w1", "w2", "w3")
FORBIDDEN_PROJECTIONS = ("wk", "wv")

IDENTITY_ADAPTER_ID = 0
DEFAULT_LORA_RANK = 8


def projection_dims(cfg) -> Dict[str, Tuple[int, int]]:
    """(d_in, d_out) per adapted projection for a TransformerConfig —
    the ONE place the pool shapes come from, shared by the registry and
    the load-time shape validation."""
    attn = cfg.n_heads * cfg.head_dim
    return {
        "wq": (cfg.dim, attn),
        "wo": (attn, cfg.dim),
        "w1": (cfg.dim, cfg.ffn_dim),
        "w2": (cfg.ffn_dim, cfg.dim),
        "w3": (cfg.dim, cfg.ffn_dim),
    }


def _row_write_op():
    """Jitted pool-row writes, process-shared like the batcher's
    _page_table_ops (jax.jit caches per shape). NOT donated — see the
    module docstring: the old pool buffers must stay valid for any
    dispatch that already fetched them."""
    op = _row_write_op.__dict__.get("op")
    if op is not None:
        return op
    import jax

    @jax.jit
    def set_row(pool, row, value):
        return pool.at[row].set(value)

    _row_write_op.op = set_row
    return set_row


class _AdapterMeta:
    __slots__ = ("name", "row", "alpha", "pins")

    def __init__(self, name: str, row: int, alpha: float):
        self.name = name
        self.row = row
        self.alpha = alpha
        self.pins = 0  # live slots referencing this adapter


class AdapterRegistry:
    """See module docstring. ``cfg`` is the model's TransformerConfig
    (pool shapes derive from it), ``rank`` the shared pool rank (every
    adapter in one pool has one rank — the gather is dense), and
    ``max_adapters`` the pool row count INCLUDING the reserved identity
    row 0."""

    def __init__(self, cfg, rank: int, max_adapters: int = 8,
                 dtype: Optional[Any] = None):
        import jax
        import jax.numpy as jnp

        if rank < 1:
            raise ValueError(f"lora_rank={rank} must be >= 1")
        if max_adapters < 2:
            raise ValueError(
                f"lora_max_adapters={max_adapters} must be >= 2 (row 0 is "
                f"the reserved identity adapter)")
        self.cfg = cfg
        self.rank = int(rank)
        self.max_adapters = int(max_adapters)
        self.n_layers = int(cfg.n_layers)
        self.dtype = jnp.dtype(dtype if dtype is not None else cfg.dtype)
        self._lock = threading.Lock()
        self._dims = projection_dims(cfg)
        # dense pools: per projection (A [N, L, d_in, r], B [N, L, r, d_out])
        # plus the per-adapter scale vector [N] (alpha / rank; 0 for
        # identity and for freed rows). Row 0 stays all-zero forever.
        N, L, r = self.max_adapters, self.n_layers, self.rank
        pool: Dict[str, Any] = {}
        for proj, (din, dout) in self._dims.items():
            pool[proj] = (
                jax.jit(lambda s=(N, L, din, r): jnp.zeros(s, self.dtype))(),
                jax.jit(lambda s=(N, L, r, dout): jnp.zeros(s, self.dtype))(),
            )
        pool["scale"] = jnp.zeros((N,), jnp.float32)
        self._pool = pool
        self._by_name: Dict[str, _AdapterMeta] = {}
        self._by_row: Dict[int, _AdapterMeta] = {}
        self._free_rows: List[int] = list(range(self.max_adapters - 1, 0, -1))
        self.evictions_total = 0
        self.loads_total = 0
        self._pool_bytes = sum(
            int(leaf.nbytes) for leaf in jax.tree.leaves(pool))

    # ------------------------------------------------------------------
    # validation (shared by load / load_uri)
    # ------------------------------------------------------------------
    def _validate(self, name: str, weights: Dict[str, Any], rank: int):
        if not name:
            raise ValueError("adapter name must be non-empty (row 0 is the "
                             "reserved identity adapter)")
        if rank != self.rank:
            raise ValueError(
                f"adapter {name!r} rank {rank} != pool rank {self.rank}: "
                f"one dense pool holds one rank (size the pool for the "
                f"largest adapter and zero-pad smaller ones offline)")
        for proj in weights:
            base = proj.lower()
            if base in FORBIDDEN_PROJECTIONS or base.startswith(("wk", "wv")):
                raise ValueError(
                    f"adapter {name!r} carries factors for {proj!r}: k/v "
                    f"projections are never adapted — adapting them would "
                    f"fork the KV cache per tenant and break cross-tenant "
                    f"page/prefix sharing (docs/multitenancy.md, the "
                    f"KV-purity invariant)")
            if base not in self._dims:
                raise ValueError(
                    f"adapter {name!r} names unknown projection {proj!r}: "
                    f"expected a subset of {ADAPTED_PROJECTIONS}")
        L, r = self.n_layers, self.rank
        for proj, (a, b) in weights.items():
            din, dout = self._dims[proj]
            a = np.asarray(a)
            b = np.asarray(b)
            if a.shape != (L, din, r) or b.shape != (L, r, dout):
                raise ValueError(
                    f"adapter {name!r} {proj} factors have shapes "
                    f"{a.shape}/{b.shape}; expected A {(L, din, r)} and "
                    f"B {(L, r, dout)} for this model config")

    # ------------------------------------------------------------------
    # load / evict
    # ------------------------------------------------------------------
    def load(self, name: str, weights: Dict[str, Any],
             alpha: Optional[float] = None,
             rank: Optional[int] = None) -> int:
        """Load (or replace) adapter ``name`` from in-memory factors
        ``{proj: (A [L, d_in, r], B [L, r, d_out])}`` — a subset of
        ADAPTED_PROJECTIONS; missing projections contribute zero delta.
        Returns the adapter id (pool row). Replacing a PINNED adapter
        raises — a live slot's gather must never change under it."""
        import jax.numpy as jnp

        alpha = float(alpha if alpha is not None else self.rank)
        self._validate(name, weights, int(rank or self.rank))
        set_row = _row_write_op()
        with self._lock:
            meta = self._by_name.get(name)
            if meta is not None and meta.pins > 0:
                raise ValueError(
                    f"adapter {name!r} is pinned by {meta.pins} live "
                    f"slot(s); a reload would change an in-flight "
                    f"request's weights mid-generation")
            if meta is None:
                if not self._free_rows:
                    raise ValueError(
                        f"adapter pool full ({self.max_adapters - 1} rows "
                        f"+ identity); evict an unpinned adapter first")
                meta = _AdapterMeta(name, self._free_rows.pop(), alpha)
                self._by_name[name] = meta
                self._by_row[meta.row] = meta
            meta.alpha = alpha
            row = jnp.asarray(meta.row, jnp.int32)
            pool = dict(self._pool)
            L, r = self.n_layers, self.rank
            for proj, (din, dout) in self._dims.items():
                if proj in weights:
                    a = np.asarray(weights[proj][0], np.float32)
                    b = np.asarray(weights[proj][1], np.float32)
                else:
                    a = np.zeros((L, din, r), np.float32)
                    b = np.zeros((L, r, dout), np.float32)
                A, B = pool[proj]
                pool[proj] = (
                    set_row(A, row, jnp.asarray(a, self.dtype)),
                    set_row(B, row, jnp.asarray(b, self.dtype)),
                )
            pool["scale"] = set_row(
                pool["scale"], row,
                jnp.asarray(alpha / self.rank, jnp.float32))
            self._pool = pool
            self.loads_total += 1
            logger.info("loaded adapter %r into pool row %d (alpha=%s)",
                        name, meta.row, alpha)
            return meta.row

    def load_uri(self, name: str, uri: str) -> int:
        """Fetch an adapter artifact through the storage layer and load
        it: a directory holding ``adapter.json`` ({"rank": r, "alpha": a})
        and ``weights.npz`` with ``<proj>.A`` / ``<proj>.B`` arrays."""
        from seldon_core_tpu import storage

        path = storage.download(uri)
        with open(os.path.join(path, "adapter.json")) as f:
            meta = json.load(f)
        blob = np.load(os.path.join(path, "weights.npz"))
        weights: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        for key in blob.files:
            proj, _, part = key.rpartition(".")
            if part not in ("A", "B"):
                raise ValueError(
                    f"adapter {name!r} weights.npz key {key!r} must end in "
                    f".A or .B")
            a, b = weights.get(proj, (None, None))
            if part == "A":
                weights[proj] = (blob[key], b)
            else:
                weights[proj] = (a, blob[key])
        for proj, (a, b) in weights.items():
            if a is None or b is None:
                raise ValueError(
                    f"adapter {name!r} projection {proj!r} needs both "
                    f"{proj}.A and {proj}.B in weights.npz")
        return self.load(name, weights, alpha=meta.get("alpha"),
                         rank=int(meta.get("rank", self.rank)))

    def evict(self, name: str) -> bool:
        """Free adapter ``name``'s pool row for reuse. Returns False —
        and frees NOTHING — while any live slot pins it: the refcount
        invariant (acceptance bar, schedules-proven). The row's factors
        are zeroed so a stale id gathered by mistake reads as identity,
        never as another tenant's weights."""
        import jax.numpy as jnp

        set_row = _row_write_op()
        with self._lock:
            meta = self._by_name.get(name)
            if meta is None:
                return False
            if meta.pins > 0:
                return False
            del self._by_name[name]
            del self._by_row[meta.row]
            row = jnp.asarray(meta.row, jnp.int32)
            pool = dict(self._pool)
            L, r = self.n_layers, self.rank
            for proj, (din, dout) in self._dims.items():
                A, B = pool[proj]
                pool[proj] = (
                    set_row(A, row, jnp.zeros((L, din, r), self.dtype)),
                    set_row(B, row, jnp.zeros((L, r, dout), self.dtype)),
                )
            pool["scale"] = set_row(pool["scale"], row,
                                    jnp.asarray(0.0, jnp.float32))
            self._pool = pool
            self._free_rows.append(meta.row)
            self.evictions_total += 1
            logger.info("evicted adapter %r (pool row %d freed)",
                        name, meta.row)
            return True

    # ------------------------------------------------------------------
    # serving-path surface
    # ------------------------------------------------------------------
    def resolve(self, name: Optional[str]) -> int:
        """Adapter id for ``name`` (None/"" = the identity adapter).
        Raises KeyError on an unknown name — the transport maps it to a
        400, never a silent base-model fallback."""
        if not name:
            return IDENTITY_ADAPTER_ID
        with self._lock:
            meta = self._by_name.get(name)
            if meta is None:
                raise KeyError(
                    f"unknown adapter {name!r}: load it first "
                    f"(loaded: {sorted(self._by_name)})")
            return meta.row

    def resolve_and_pin(self, name: Optional[str]) -> int:
        """``resolve`` + ``pin`` under ONE lock hold — the admission
        path's entry point. Separate resolve()-then-pin() calls would
        leave a gap where an evict + load repurposes the row, silently
        pinning (and serving) ANOTHER tenant's adapter; atomically the
        name either maps to its live row (pinned before the lock drops,
        so no evict can slip in) or raises KeyError (-> 400 at the
        transport)."""
        if not name:
            return IDENTITY_ADAPTER_ID
        with self._lock:
            meta = self._by_name.get(name)
            if meta is None:
                raise KeyError(
                    f"unknown adapter {name!r}: load it first "
                    f"(loaded: {sorted(self._by_name)})")
            meta.pins += 1
            return meta.row

    def pin(self, adapter_id: int) -> None:
        """One live slot now references ``adapter_id`` (admission path).
        Identity pins are no-ops — row 0 can never be evicted. Pinning a
        freed row raises: the request raced an evict and must fail
        loudly, not serve zeros it didn't ask for."""
        if adapter_id == IDENTITY_ADAPTER_ID:
            return
        with self._lock:
            meta = self._by_row.get(adapter_id)
            if meta is None:
                raise KeyError(f"adapter id {adapter_id} is not loaded")
            meta.pins += 1

    def unpin(self, adapter_id: int) -> None:
        if adapter_id == IDENTITY_ADAPTER_ID:
            return
        with self._lock:
            meta = self._by_row.get(adapter_id)
            if meta is None or meta.pins <= 0:
                raise ValueError(
                    f"unbalanced unpin of adapter id {adapter_id}")
            meta.pins -= 1

    def pool(self) -> Dict[str, Any]:
        """The current pool pytree ({proj: (A, B), "scale": [N]}), passed
        as an argument into every adapted compiled step. The returned
        references stay valid even if a load swaps the pool right after —
        loads never donate (module docstring)."""
        with self._lock:
            return self._pool

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._by_name)

    def refs_of(self, name: str) -> int:
        with self._lock:
            meta = self._by_name.get(name)
            return 0 if meta is None else meta.pins

    def stats(self) -> Dict[str, Any]:
        """One consistent snapshot for llm_stats -> /metrics:
        seldon_llm_adapter_{loaded,evictions_total,pool_bytes}."""
        with self._lock:
            return {
                "adapter_loaded": len(self._by_name),
                "adapter_capacity": self.max_adapters - 1,
                "adapter_evictions_total": self.evictions_total,
                "adapter_loads_total": self.loads_total,
                "adapter_pool_bytes": self._pool_bytes,
                "adapter_rank": self.rank,
                "adapter_pins": {m.name: m.pins
                                 for m in self._by_name.values()},
            }
