from seldon_core_tpu.runtime.engine import GraphEngine, PredictorState

__all__ = ["GraphEngine", "PredictorState"]
