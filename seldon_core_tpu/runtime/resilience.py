"""Resilience primitives: deadline budgets, circuit breakers, load shedding.

The reference orchestrator's only robustness tools are per-hop retries and
per-deployment timeout annotations (`InternalPredictionService.java:82-91`,
mirrored in runtime/remote.py). This module adds the standard serving-system
triad on top (Envoy/Finagle style), shared by every transport and the
in-process graph engine:

- **Deadline**: a request-level time budget threaded from the transport edge
  (REST header ``Seldon-Deadline-Ms`` / the gRPC deadline) through engine
  node execution into remote hops. Each hop gets ``min(per-hop timeout,
  remaining budget)``; an exhausted budget short-circuits downstream nodes
  with 504/``DEADLINE_EXCEEDED`` instead of executing them. Propagates via a
  contextvar so graph wrappers (MicroBatcher, IPC drain) need no signature
  changes.
- **CircuitBreaker**: per-node closed -> open (after N consecutive failures)
  -> half-open probe -> closed. Wraps remote and async node calls in the
  engine; a ROUTER reroutes around an open child and a COMBINER drops open
  branches when the graph allows partial responses.
- **AdmissionController**: bounded in-flight + bounded queue at the
  transport edge. Overflow sheds immediately (503 + ``Retry-After`` /
  ``RESOURCE_EXHAUSTED``) so overload fails fast instead of building an
  unbounded latency queue.

Everything takes an injectable monotonic ``clock`` so the fault-injection
harness (seldon_core_tpu.testing.faults) can drive state transitions
deterministically — no wall-clock sleeps in tests.
"""

from __future__ import annotations

import asyncio
import threading
import time
from collections import deque
from contextlib import contextmanager
from contextvars import ContextVar
from typing import Any, Callable, Dict, List, Optional

from seldon_core_tpu.contracts.payload import SeldonError

# ---------------------------------------------------------------------------
# Annotations (docs/resilience.md catalogs these)
# ---------------------------------------------------------------------------
ANNOTATION_DEADLINE_DEFAULT = "seldon.io/deadline-default-ms"
ANNOTATION_BREAKER_FAILURES = "seldon.io/circuit-breaker-max-failures"
ANNOTATION_BREAKER_RESET = "seldon.io/circuit-breaker-reset-ms"
ANNOTATION_ALLOW_PARTIAL = "seldon.io/allow-partial"
ANNOTATION_MAX_INFLIGHT = "seldon.io/max-inflight"
ANNOTATION_MAX_QUEUE = "seldon.io/max-queue"
ANNOTATION_RETRY_AFTER = "seldon.io/shed-retry-after-s"

DEADLINE_HEADER = "Seldon-Deadline-Ms"
DEADLINE_GRPC_METADATA = "seldon-deadline-ms"

DEFAULT_BREAKER_FAILURES = 5
DEFAULT_BREAKER_RESET_S = 30.0
DEFAULT_RETRY_AFTER_S = 1


def _parse_float(annotations: Dict[str, str], key: str, default: Optional[float]) -> Optional[float]:
    try:
        return float(annotations[key])
    except (KeyError, TypeError, ValueError):
        return default


def _parse_int(annotations: Dict[str, str], key: str, default: int) -> int:
    try:
        return int(annotations[key])
    except (KeyError, TypeError, ValueError):
        return default


# ---------------------------------------------------------------------------
# Deadline budgets
# ---------------------------------------------------------------------------


class DeadlineExceeded(SeldonError):
    """Request budget exhausted. Maps to HTTP 504 / gRPC DEADLINE_EXCEEDED."""

    def __init__(self, message: str):
        super().__init__(message, status_code=504, reason="DEADLINE_EXCEEDED")


class Deadline:
    """A monotonic-clock time budget for one request.

    ``clock`` is any zero-arg callable returning monotonic seconds; the fault
    harness passes a manually-advanced clock for deterministic tests.
    """

    __slots__ = ("budget_s", "clock", "deadline_t")

    def __init__(self, budget_s: float, clock: Callable[[], float] = time.monotonic):
        self.budget_s = float(budget_s)
        self.clock = clock
        self.deadline_t = clock() + self.budget_s

    @classmethod
    def from_ms(cls, ms: float, clock: Callable[[], float] = time.monotonic) -> "Deadline":
        return cls(float(ms) / 1000.0, clock=clock)

    def remaining_s(self) -> float:
        return self.deadline_t - self.clock()

    def remaining_ms(self) -> float:
        return self.remaining_s() * 1000.0

    @property
    def expired(self) -> bool:
        return self.remaining_s() <= 0.0

    def check(self, where: str = "") -> None:
        rem = self.remaining_s()
        if rem <= 0.0:
            at = f" at {where}" if where else ""
            raise DeadlineExceeded(
                f"deadline exceeded{at}: budget {self.budget_s * 1000:.0f}ms "
                f"overrun by {-rem * 1000:.0f}ms"
            )


# The in-flight request's deadline. Set by transports (or engine.predict when
# given an explicit deadline) and read by remote hops; contextvars propagate
# through awaits within a task and through call_soon_threadsafe, covering the
# REST app, the gRPC engine loop, and the sync _drive_sync path alike.
DEADLINE: ContextVar[Optional[Deadline]] = ContextVar("seldon_deadline", default=None)


def current_deadline() -> Optional[Deadline]:
    return DEADLINE.get()


@contextmanager
def deadline_scope(deadline: Optional[Deadline]):
    token = DEADLINE.set(deadline)
    try:
        yield deadline
    finally:
        DEADLINE.reset(token)


def effective_timeout(per_hop_s: Optional[float], deadline: Optional[Deadline] = None) -> Optional[float]:
    """``min(per-hop timeout, remaining budget)`` for one remote hop.

    Raises DeadlineExceeded when the budget is already spent — callers must
    not start network work they cannot finish in time.
    """
    if deadline is None:
        deadline = current_deadline()
    if deadline is None:
        return per_hop_s
    rem = deadline.remaining_s()
    if rem <= 0.0:
        raise DeadlineExceeded(
            f"deadline exceeded before remote hop: budget "
            f"{deadline.budget_s * 1000:.0f}ms already spent"
        )
    return rem if per_hop_s is None else min(per_hop_s, rem)


# ---------------------------------------------------------------------------
# Circuit breakers
# ---------------------------------------------------------------------------

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class BreakerOpen(SeldonError):
    """Call rejected because the node's breaker is open."""

    def __init__(self, node: str, retry_in_s: float):
        super().__init__(
            f"circuit breaker open for node {node!r} (retry in {max(retry_in_s, 0.0):.1f}s)",
            status_code=503,
            reason="CIRCUIT_OPEN",
        )
        self.node = node
        self.retry_in_s = max(retry_in_s, 0.0)


class CircuitBreaker:
    """Per-node breaker: closed -> open after ``failure_threshold`` consecutive
    failures -> half-open probe after ``reset_timeout_s`` -> closed on probe
    success (re-open on probe failure).

    Thread-safe: the engine may be driven from several event loops and the
    IPC drain's inline threads at once. ``clock`` is mutable so tests can
    swap in a fake clock post-build (``engine.unit_by_name(n).breaker.clock``).
    """

    def __init__(
        self,
        name: str,
        failure_threshold: int = DEFAULT_BREAKER_FAILURES,
        reset_timeout_s: float = DEFAULT_BREAKER_RESET_S,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.name = name
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout_s = float(reset_timeout_s)
        self.clock = clock
        self.state = CLOSED
        self.consecutive_failures = 0
        self.opened_at = 0.0
        self._probe_inflight = False
        self.transitions: Dict[str, int] = {CLOSED: 0, OPEN: 0, HALF_OPEN: 0}
        self.rejected_total = 0
        self.on_transition: Optional[Callable[[str, str], None]] = None
        self._lock = threading.Lock()

    # -- state machine --------------------------------------------------
    def _transition(self, to: str) -> None:
        self.state = to
        self.transitions[to] += 1
        if to == OPEN:
            self.opened_at = self.clock()
            self.consecutive_failures = 0
        if to != HALF_OPEN:
            self._probe_inflight = False
        cb = self.on_transition
        if cb is not None:
            try:
                cb(self.name, to)
            except Exception:
                pass  # observability must never fail the data path

    def allow(self) -> bool:
        """May a call proceed now? Consumes the half-open probe slot."""
        with self._lock:
            if self.state == CLOSED:
                return True
            if self.state == OPEN:
                if self.clock() - self.opened_at >= self.reset_timeout_s:
                    self._transition(HALF_OPEN)
                else:
                    self.rejected_total += 1
                    return False
            # HALF_OPEN: exactly one probe at a time
            if self._probe_inflight:
                self.rejected_total += 1
                return False
            self._probe_inflight = True
            return True

    def available(self) -> bool:
        """Non-mutating health check (routers peek before routing): would a
        call be allowed without consuming the probe slot?"""
        with self._lock:
            if self.state == CLOSED:
                return True
            if self.state == OPEN:
                return self.clock() - self.opened_at >= self.reset_timeout_s
            return not self._probe_inflight

    def record_success(self) -> None:
        with self._lock:
            self.consecutive_failures = 0
            if self.state != CLOSED:
                self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            if self.state == HALF_OPEN:
                self._transition(OPEN)  # failed probe: back to open
                return
            self.consecutive_failures += 1
            if self.state == CLOSED and 0 < self.failure_threshold <= self.consecutive_failures:
                self._transition(OPEN)

    def release_probe(self) -> None:
        """Probe outcome unknown (e.g. the call was cancelled): free the
        half-open probe slot without judging the node, so the next call can
        probe again instead of the breaker wedging half-open forever."""
        with self._lock:
            if self.state == HALF_OPEN:
                self._probe_inflight = False

    def trip(self) -> None:
        """Force-open regardless of the failure count: the caller OBSERVED
        the node dead (batcher loop crashed, heartbeat stale) rather than
        inferring it from consecutive errors — fleet ejection
        (docs/resilience.md "Fleet fault tolerance"). Reinstatement still
        goes through the normal half-open probe path."""
        with self._lock:
            if self.state != OPEN:
                self._transition(OPEN)

    def retry_in_s(self) -> float:
        with self._lock:
            if self.state != OPEN:
                return 0.0
            return self.reset_timeout_s - (self.clock() - self.opened_at)

    def state_code(self) -> int:
        """0 closed, 1 half-open, 2 open (the metrics gauge encoding).
        Locked like every other state read: the gauge scrape runs on the
        metrics thread while transitions happen on the serving path."""
        with self._lock:
            return _STATE_CODES[self.state]


# ---------------------------------------------------------------------------
# Admission control (load shedding)
# ---------------------------------------------------------------------------


class ShedError(SeldonError):
    """Request shed at admission: server at capacity and queue full."""

    def __init__(self, message: str, retry_after_s: float = DEFAULT_RETRY_AFTER_S):
        super().__init__(message, status_code=503, reason="RESOURCE_EXHAUSTED")
        self.retry_after_s = retry_after_s


class AdmissionController:
    """Bounded in-flight limit + bounded FIFO queue with shed-on-full.

    ``max_inflight <= 0`` disables admission control entirely (the default:
    existing deployments keep today's unbounded behavior until they opt in).
    Works for both async callers (REST handlers ``await acquire()``) and
    thread-pool callers (gRPC servicers call ``acquire_sync()``): waiters of
    both kinds share one FIFO so ordering is transport-fair.
    """

    def __init__(
        self,
        max_inflight: int = 0,
        max_queue: int = 0,
        retry_after_s: float = DEFAULT_RETRY_AFTER_S,
        retry_after_fn: Optional[Callable[[], float]] = None,
    ):
        self.max_inflight = int(max_inflight)
        self.max_queue = int(max_queue)
        self.retry_after_s = float(retry_after_s)
        # Dynamic Retry-After (docs/resilience.md "Dynamic backoff"):
        # when set, every shed's Retry-After is refined through this
        # callable — transports wire it to the component's scaling
        # snapshot (observability/timeline.py retry_after_hint) so
        # backoff scales with the live queue depth / drain rate instead
        # of the fixed constant. Called OUTSIDE self._lock: the hint
        # reads batcher/allocator state under THEIR locks, and calling
        # through while holding ours would create a cross-module lock
        # order for an error path.
        self.retry_after_fn = retry_after_fn
        self.inflight = 0
        self.shed_total = 0
        self.admitted_total = 0
        self._waiters: deque = deque()  # ("async", loop, future) | ("sync", event_box)
        self._lock = threading.Lock()

    @classmethod
    def from_annotations(
        cls, annotations: Optional[Dict[str, str]], env: Optional[Dict[str, str]] = None
    ) -> "AdmissionController":
        """Annotations win over env vars (SELDON_MAX_INFLIGHT / SELDON_MAX_QUEUE
        / SELDON_SHED_RETRY_AFTER_S); both absent means disabled."""
        import os

        env = dict(env if env is not None else os.environ)
        ann = dict(annotations or {})

        def pick(key: str, env_key: str, default: float) -> float:
            for source, k in ((ann, key), (env, env_key)):
                try:
                    return float(source[k])
                except (KeyError, TypeError, ValueError):
                    continue
            return default

        return cls(
            max_inflight=int(pick(ANNOTATION_MAX_INFLIGHT, "SELDON_MAX_INFLIGHT", 0)),
            max_queue=int(pick(ANNOTATION_MAX_QUEUE, "SELDON_MAX_QUEUE", 0)),
            retry_after_s=pick(ANNOTATION_RETRY_AFTER, "SELDON_SHED_RETRY_AFTER_S", DEFAULT_RETRY_AFTER_S),
        )

    @property
    def enabled(self) -> bool:
        return self.max_inflight > 0

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._waiters)

    def _shed(self) -> ShedError:
        """Build (and count) one shed. Callers hold ``self._lock``: the
        counter bump is a read-modify-write and the message reads the
        waiter queue — unlocked, concurrent sheds lose counts
        (tests/test_schedules.py replays the exact interleaving). The
        dynamic Retry-After refinement happens in ``_refine`` AFTER the
        lock is released — never here."""
        self.shed_total += 1
        return ShedError(
            f"server at capacity: {self.inflight} in flight, "
            f"{len(self._waiters)}/{self.max_queue} queued",
            retry_after_s=self.retry_after_s,
        )

    def _refine(self, err: ShedError) -> ShedError:
        """Apply the dynamic Retry-After hint (``retry_after_fn``) to a
        shed built under the lock. Called OUTSIDE ``self._lock`` by
        contract (the hint reads batcher/allocator state under their own
        locks); a failing hint falls back to the constant already on the
        error."""
        fn = self.retry_after_fn
        if fn is not None:
            try:
                err.retry_after_s = float(fn())
            except Exception:
                pass  # a backoff hint must never mask the shed itself
        return err

    def _try_admit_locked(self) -> bool:
        if self.inflight < self.max_inflight:
            self.inflight += 1
            self.admitted_total += 1
            return True
        return False

    async def acquire(self) -> None:
        """Async admission: immediate slot, else queue, else ShedError."""
        if not self.enabled:
            return
        with self._lock:
            if self._try_admit_locked():
                return
            if len(self._waiters) >= self.max_queue:
                err = self._shed()
            else:
                err = None
                loop = asyncio.get_running_loop()
                fut: asyncio.Future = loop.create_future()
                self._waiters.append(("async", loop, fut))
        if err is not None:
            raise self._refine(err)
        try:
            await fut
        except asyncio.CancelledError:
            with self._lock:
                granted = fut.done() and not fut.cancelled()
            if granted:
                self.release()  # slot arrived as the client disconnected
            raise

    def acquire_sync(self, timeout_s: Optional[float] = None) -> None:
        """Thread-blocking admission for thread-pool transports (gRPC)."""
        if not self.enabled:
            return
        with self._lock:
            if self._try_admit_locked():
                return
            if len(self._waiters) >= self.max_queue:
                err = self._shed()
            else:
                err = None
                event = threading.Event()
                entry = ("sync", event)
                self._waiters.append(entry)
        if err is not None:
            raise self._refine(err)
        if not event.wait(timeout_s):
            with self._lock:
                try:
                    self._waiters.remove(entry)
                except ValueError:
                    # grant raced the timeout: the slot is ours, give it back
                    granted = True
                else:
                    granted = False
                    err = self._shed()
            if not granted:
                raise self._refine(err)
            self.release()  # outside the lock: release() takes it itself
            with self._lock:
                err = self._shed()
            raise self._refine(err)

    def release(self) -> None:
        """Finish one admitted request; hand its slot to the oldest waiter."""
        if not self.enabled:
            return
        with self._lock:
            while self._waiters:
                entry = self._waiters.popleft()
                if entry[0] == "async":
                    _, loop, fut = entry

                    def grant(f=fut):
                        if not f.done():
                            f.set_result(None)
                        else:
                            self.release()  # waiter cancelled: pass it on

                    try:
                        loop.call_soon_threadsafe(grant)
                        self.admitted_total += 1
                        return  # slot transferred, inflight unchanged
                    except RuntimeError:
                        continue  # waiter's loop is gone; try the next waiter
                else:
                    _, event = entry
                    event.set()
                    self.admitted_total += 1
                    return
            self.inflight = max(self.inflight - 1, 0)


# ---------------------------------------------------------------------------
# Engine-level config
# ---------------------------------------------------------------------------


class ResilienceConfig:
    """Per-graph resilience tuning, parsed from deployment annotations."""

    __slots__ = (
        "breaker_failures",
        "breaker_reset_s",
        "allow_partial",
        "default_deadline_ms",
        "clock",
    )

    def __init__(
        self,
        breaker_failures: int = DEFAULT_BREAKER_FAILURES,
        breaker_reset_s: float = DEFAULT_BREAKER_RESET_S,
        allow_partial: bool = False,
        default_deadline_ms: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.breaker_failures = breaker_failures
        self.breaker_reset_s = breaker_reset_s
        self.allow_partial = allow_partial
        self.default_deadline_ms = default_deadline_ms
        self.clock = clock

    @classmethod
    def from_annotations(cls, annotations: Optional[Dict[str, str]]) -> "ResilienceConfig":
        ann = dict(annotations or {})
        reset_ms = _parse_float(ann, ANNOTATION_BREAKER_RESET, DEFAULT_BREAKER_RESET_S * 1000.0)
        return cls(
            breaker_failures=_parse_int(ann, ANNOTATION_BREAKER_FAILURES, DEFAULT_BREAKER_FAILURES),
            breaker_reset_s=(reset_ms or 0.0) / 1000.0,
            allow_partial=str(ann.get(ANNOTATION_ALLOW_PARTIAL, "")).lower() in ("true", "1", "yes"),
            default_deadline_ms=_parse_float(ann, ANNOTATION_DEADLINE_DEFAULT, None),
        )

    def make_breaker(self, name: str) -> Optional[CircuitBreaker]:
        if self.breaker_failures <= 0:
            return None
        return CircuitBreaker(
            name,
            failure_threshold=self.breaker_failures,
            reset_timeout_s=self.breaker_reset_s,
            clock=self.clock,
        )


def failure_counts_for_breaker(exc: BaseException) -> bool:
    """Which errors trip a breaker: infrastructure failures (5xx, timeouts,
    transport errors), not client errors (4xx), not the breaker's own
    rejections — an open breaker must not feed back into itself — and not
    cancellation: a client disconnecting says nothing about the node, and
    impatient clients must not be able to open a healthy node's breaker."""
    if isinstance(exc, (BreakerOpen, asyncio.CancelledError)):
        return False
    if isinstance(exc, SeldonError):
        return exc.status_code >= 500
    return True


# ---------------------------------------------------------------------------
# Fleet fault tolerance: retry budget + resume marker
# ---------------------------------------------------------------------------


DEFAULT_RETRY_BUDGET_RATIO = 0.2
DEFAULT_RETRY_BUDGET_MIN = 3
DEFAULT_RETRY_BUDGET_WINDOW_S = 10.0


class RetryBudget:
    """Bounded recovery budget (docs/resilience.md "Fleet fault
    tolerance"): resumes and pre-first-token failovers re-dispatch work
    the fleet already paid for once, so a correlated failure storm (half
    the replicas die at once) could otherwise double offered load exactly
    when capacity halved. Every recovery draws from this budget — a
    sliding-window fraction of recent REQUEST traffic plus a small fixed
    floor — and exhaustion degrades to an honest ShedError
    (503 + Retry-After) instead of amplification.

    Invariant: retries granted inside any window never exceed
    ``ratio * requests_in_window + min_retries``, so fleet load is capped
    at ``(1 + ratio)`` of offered traffic plus the constant floor.

    Thread-safe: dispatch threads note requests and spend retries
    concurrently (both are read-modify-writes on the deques/counter)."""

    def __init__(self, ratio: float = DEFAULT_RETRY_BUDGET_RATIO,
                 min_retries: int = DEFAULT_RETRY_BUDGET_MIN,
                 window_s: float = DEFAULT_RETRY_BUDGET_WINDOW_S,
                 clock: Callable[[], float] = time.monotonic):
        import collections
        import threading

        self.ratio = float(ratio)
        self.min_retries = int(min_retries)
        self.window_s = float(window_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._requests = collections.deque()  # admission timestamps
        self._retries = collections.deque()   # granted-retry timestamps
        self.exhausted_total = 0

    def _prune(self, now: float) -> None:
        horizon = now - self.window_s
        while self._requests and self._requests[0] < horizon:
            self._requests.popleft()
        while self._retries and self._retries[0] < horizon:
            self._retries.popleft()

    def note_request(self) -> None:
        """One unit of organic traffic entered the fleet (grows the
        budget; never consumes it)."""
        now = self.clock()
        with self._lock:
            self._prune(now)
            self._requests.append(now)

    def try_spend(self) -> bool:
        """Atomically grant one recovery if the window has budget left.
        False means the caller must shed (503 + Retry-After), and the
        refusal is counted for /metrics."""
        now = self.clock()
        with self._lock:
            self._prune(now)
            allowed = self.ratio * len(self._requests) + self.min_retries
            if len(self._retries) < allowed:
                self._retries.append(now)
                return True
            self.exhausted_total += 1
            return False

    # the registered acquire-site name in tools/leaklint/effects.py: a
    # budget spend is the one obligation that is consumed by design (no
    # static release), but the dynamic sweep still injects at it
    take = try_spend

    def snapshot(self) -> Dict[str, float]:
        """One consistent view for stats/metrics."""
        now = self.clock()
        with self._lock:
            self._prune(now)
            return {
                "requests_in_window": len(self._requests),
                "retries_in_window": len(self._retries),
                "exhausted_total": self.exhausted_total,
            }


class ResumeJournal:
    """The fleet's token-granularity recovery journal (docs/resilience.md
    "Fleet fault tolerance"), factored out of ReplicaSet so its locking
    is a single auditable surface and its entry lifetime is a registered
    leaklint obligation: ``record()`` acquires a journal-entry, the
    dispatch loop's ``finally`` must ``discard()`` it on every path
    (tools/leaklint/effects.py).

    Appends happen on batcher worker threads while the retry loop reads
    ``delivered()`` — every access takes the journal's own lock, so a
    mid-append snapshot can never tear (the PR 16 reconstruction in
    tests/test_schedules.py is the exact interleaving this prevents).
    ``append``/``delivered`` on a discarded id are no-ops: a straggler
    token from a crashed replica's worker thread can land after the
    dispatch completed, and it must not resurrect the entry."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[int, Any] = {}
        self._seq = 0

    def record(self, entry: Any) -> int:
        """Admit one in-flight generation; returns its journal id. The
        caller owes a ``discard(jid)`` on every exit path."""
        with self._lock:
            self._seq += 1
            jid = self._seq
            self._entries[jid] = entry
            return jid

    def append(self, jid: int, token: int) -> None:
        """One delivered token, recorded BEFORE the client sees it — a
        resume then skips exactly the delivered prefix (at-most-once)."""
        with self._lock:
            entry = self._entries.get(jid)
            if entry is not None:
                entry.tokens.append(int(token))

    def delivered(self, jid: int) -> List[int]:
        """Consistent snapshot of the tokens delivered so far ([] after
        discard)."""
        with self._lock:
            entry = self._entries.get(jid)
            return list(entry.tokens) if entry is not None else []

    def get(self, jid: int) -> Optional[Any]:
        """The live entry itself (None after discard) — test/debug
        surface; production code goes through append/delivered."""
        with self._lock:
            return self._entries.get(jid)

    def discard(self, jid: int) -> None:
        """End of the entry's lifetime (idempotent)."""
        with self._lock:
            self._entries.pop(jid, None)

    def depth(self) -> int:
        """Entries in flight — exported as
        ``fleet_resume_journal_depth`` and asserted back to zero by the
        leak canary (tests/conftest.py)."""
        with self._lock:
            return len(self._entries)


class ResumeMarker:
    """In-band stream event (never a token): a recovered generation
    re-attached after ``tokens_delivered`` already-delivered tokens.
    Flows through the on_token path so SSE emits a ``resumed`` data event
    and gRPC a ``resumed`` meta chunk at the exact stream position where
    the failover happened; transports must never decode it."""

    __slots__ = ("tokens_delivered",)

    def __init__(self, tokens_delivered: int):
        self.tokens_delivered = int(tokens_delivered)
