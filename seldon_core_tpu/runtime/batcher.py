"""Continuous batching for LLM decode.

The engine-side request batcher of the BASELINE.json north star ("the
orchestrator's gRPC request batcher shards inference-graph traffic across a
v5e slice"), specialised for autoregressive decode: requests join and leave a
fixed pool of cache slots *between decode steps*, so one compiled decode
program serves overlapping requests at arbitrary arrival times — no
head-of-line blocking on the longest generation, no recompilation.

Design (all shapes static):
- one slot-batched KV cache [S, max_len, ...] lives on device;
- admission: a single-prompt prefill (compiled per length bucket) produces a
  1-sequence cache which is written into a free slot (jitted insert);
- every step runs ONE jitted decode over all S slots with per-slot cache
  offsets (models/transformer.py vector ``cache_index``); inactive slots
  compute garbage into their own slot, which the next insert overwrites;
- completion: EOS or per-request max_new_tokens frees the slot.

The transformer's position-tracked cache (PAD_POS masking) is what makes the
mixed-occupancy batch exact: each slot only attends to its own written
positions.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, List, Optional, Sequence

import numpy as np

from seldon_core_tpu.servers.llmserver import LLMServer, _bucket

logger = logging.getLogger(__name__)


class _Slot:
    __slots__ = ("future", "tokens", "true_len", "n_new", "max_new", "active",
                 "on_token")

    def __init__(self):
        self.active = False
        self.future: Optional[asyncio.Future] = None
        self.tokens: List[int] = []
        self.true_len = 0
        self.n_new = 0
        self.max_new = 0
        self.on_token: Optional[Any] = None


class BatcherService:
    """Owns a ContinuousBatcher on a dedicated event-loop thread so every
    transport can reach ONE shared batch: async REST handlers await
    ``submit``, the sync gRPC servicer blocks on ``submit_sync`` — either
    way the request joins the in-flight decode batch instead of running its
    own ``generate()``. Created lazily per component by
    ``get_batcher_service`` (keyed on the component, so REST and gRPC in one
    process share slots)."""

    def __init__(self, server: "LLMServer", max_slots: int = 4):
        import threading

        self._loop = asyncio.new_event_loop()
        threading.Thread(target=self._loop.run_forever, name="batcher-loop",
                         daemon=True).start()
        max_len = getattr(server, "continuous_batching_max_len", None)

        async def make():
            return ContinuousBatcher(server, max_slots=max_slots,
                                     max_len=max_len)

        self.batcher = asyncio.run_coroutine_threadsafe(make(), self._loop).result()
        self.submitted = 0

    def submit_sync(self, prompt: Any, max_new_tokens: Optional[int] = None,
                    timeout_s: float = 600.0,
                    info: Optional[dict] = None) -> List[int]:
        self.submitted += 1
        return asyncio.run_coroutine_threadsafe(
            self.batcher.submit(prompt, max_new_tokens, info=info), self._loop
        ).result(timeout_s)

    async def submit(self, prompt: Any, max_new_tokens: Optional[int] = None,
                     on_token: Optional[Any] = None,
                     info: Optional[dict] = None) -> List[int]:
        self.submitted += 1
        cfut = asyncio.run_coroutine_threadsafe(
            self.batcher.submit(prompt, max_new_tokens, on_token=on_token,
                                info=info),
            self._loop)
        return await asyncio.wrap_future(cfut)

    def close(self) -> None:
        asyncio.run_coroutine_threadsafe(self.batcher.close(), self._loop).result(30)
        self._loop.call_soon_threadsafe(self._loop.stop)


# created at import time: a lazily-created lock would itself race, which is
# the exact bug this lock exists to prevent
import threading as _threading

_service_init_lock = _threading.Lock()


def _init_lock():
    return _service_init_lock


def get_batcher_service(component: Any) -> Optional[BatcherService]:
    """The component's shared BatcherService, created on first use when the
    component opted in (``continuous_batching`` slots > 0) and exposes the
    LLM generate surface; None otherwise. Creation is locked: the first REST
    request (event loop) and first gRPC request (worker thread) can race,
    and two batchers would each allocate slot caches and step the device."""
    svc = getattr(component, "_batcher_service", None)
    if svc is not None:
        return svc  # reuse even when batching is off (streaming's 1-slot svc)
    slots = int(getattr(component, "continuous_batching", 0) or 0)
    if slots <= 0 or not hasattr(component, "generate"):
        return None
    with _init_lock():
        svc = getattr(component, "_batcher_service", None)
        if svc is None:
            svc = BatcherService(component, max_slots=slots)
            component._batcher_service = svc
    return svc


def ensure_stream_service(component: Any) -> BatcherService:
    """Streaming without continuous batching: one shared 1-slot service per
    component (same double-checked lock; never one per request)."""
    svc = get_batcher_service(component)
    if svc is not None:
        return svc
    with _init_lock():
        svc = getattr(component, "_batcher_service", None)
        if svc is None:
            svc = BatcherService(component, max_slots=1)
            component._batcher_service = svc
    return svc


class ContinuousBatcher:
    def __init__(
        self,
        server: LLMServer,
        max_slots: int = 4,
        max_len: Optional[int] = None,
        len_buckets: Optional[Sequence[int]] = None,
    ):
        server.load()
        self.server = server
        self.S = int(max_slots)
        cfg = server._cfg
        # Slot caches are HBM-resident for the batcher's whole life (S slots
        # x max_len x KV bytes/token — ~0.5 MB/token at 7B), so size them to
        # what serving actually admits: prompts bucket to len_buckets with
        # one round-up step past the top bucket (_bucket), plus decode
        # headroom. Defaulting to the model's full trained context instead
        # (4k at 7B) allocates 17 GB of KV and OOMs the chip before the
        # first request. Prompts longer than 2x the top bucket truncate to
        # the cache (admit keeps the TAIL, same rule as before); a
        # deployment expecting longer prompts passes max_len explicitly
        # (LLMServer.continuous_batching_max_len).
        self.len_buckets = tuple(len_buckets or server.len_buckets)
        if max_len is not None and int(max_len) <= 0:
            # 0/negative means "unset" from every caller's point of view;
            # taking it literally would produce plen=min(...,-1) nonsense
            # tail slicing (ADVICE.md round 5)
            max_len = None
        if max_len is None:
            max_len = min(2 * max(self.len_buckets), cfg.max_seq_len) + max(
                int(server.max_new_tokens), 1
            )
        self.max_len = int(max_len)
        self.eos_id = server.eos_id
        self._slots = [_Slot() for _ in range(self.S)]
        from collections import deque

        self._pending: Any = deque()  # FIFO, peek-without-pop on full slots
        self._wakeup = asyncio.Event()
        self._closed = False
        self._task: Optional[asyncio.Task] = None
        self._build()
        # host mirrors of per-slot decode state
        self._last_tok = np.zeros((self.S,), np.int32)
        self._next_pos = np.zeros((self.S,), np.int32)

    # ------------------------------------------------------------------
    def _build(self):
        import jax
        import jax.numpy as jnp

        from seldon_core_tpu.models.transformer import init_kv_caches

        from functools import partial

        server, cfg = self.server, self.server._cfg
        module = server._module
        # slot caches inherit the server's KV storage format (int8 halves
        # the per-step attention read traffic — the dominant b8 term in
        # benchmarks/DECODE_NOTES.md)
        self._caches = jax.jit(
            lambda: init_kv_caches(cfg, self.S, self.max_len, server.kv_cache_dtype)
        )()
        self._cache_nbytes = sum(
            int(getattr(leaf, "nbytes", 0)) for leaf in jax.tree.leaves(self._caches)
        )

        # donate the big slot cache through both mutating jits (insert and
        # the decode step): self._caches is reassigned from the output each
        # time, so XLA aliases the buffers and updates in place instead of
        # copying S x max_len of KV per call
        @partial(jax.jit, donate_argnums=(0,))
        def insert(big, small, slot):
            return jax.tree.map(lambda b, s: b.at[slot].set(s[0]), big, small)

        self._insert = insert

        top_k = server.top_k
        # int8 serving: dequant inside the jit exactly like the server's
        # prefill/decode paths (XLA fuses it into the matmuls; the int8
        # copy stays the resident one)
        deq = server._dequant

        @partial(jax.jit, donate_argnums=(1,))
        def decode_step(params, caches, last_tok, next_pos, key, temperature):
            logits, caches = module.apply(
                deq(params),
                last_tok[:, None],
                positions=next_pos[:, None],
                caches=caches,
                cache_index=next_pos,
            )
            lg = logits[:, -1].astype(jnp.float32)
            greedy = jnp.argmax(lg, axis=-1)
            k = min(top_k, lg.shape[-1])
            topv, topi = jax.lax.top_k(lg, k)
            draw = jax.random.categorical(key, topv / jnp.maximum(temperature, 1e-6))
            sampled = jnp.take_along_axis(topi, draw[:, None], axis=-1)[:, 0]
            return caches, jnp.where(temperature <= 0.0, greedy, sampled)

        self._decode_step = decode_step
        self._rng = jax.random.PRNGKey(server.seed)
        self._temp = jnp.asarray(server.temperature, jnp.float32)

    # ------------------------------------------------------------------
    async def submit(self, prompt: Any, max_new_tokens: Optional[int] = None,
                     on_token: Optional[Any] = None,
                     info: Optional[dict] = None) -> List[int]:
        """prompt: str or token sequence. Resolves to generated token ids.

        ``on_token(tok)`` (optional) fires for every generated token as it is
        decoded and ``on_token(None)`` once at completion — from a worker
        thread, so the callback must be thread-safe (streaming transports
        bridge it onto their loop with call_soon_threadsafe).

        ``info`` (optional dict) is filled in-place at admission with
        anything the caller should surface to the client — today the
        ``truncated_prompt`` record when the slot cache is smaller than the
        prompt (transports attach it to the response meta)."""
        if self._closed:
            raise RuntimeError("batcher closed")
        if isinstance(prompt, str):
            ids = self.server._tokenizer.encode(prompt)
        else:
            ids = [int(t) for t in np.asarray(prompt).ravel()]
        if not ids:
            raise ValueError("empty prompt")
        self._loop = asyncio.get_running_loop()
        fut: asyncio.Future = self._loop.create_future()
        self._pending.append(
            (ids, int(max_new_tokens or self.server.max_new_tokens), fut,
             on_token, info))
        self._ensure_running()
        self._wakeup.set()
        return await fut

    def _ensure_running(self):
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(self._run())

    def _resolve(self, fut: asyncio.Future, result=None, exc: Optional[BaseException] = None):
        """Thread-safe future completion: _finish runs inside asyncio.to_thread,
        and Future.set_result must happen on the loop thread."""

        def do():
            if fut.done():
                return
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(result)

        self._loop.call_soon_threadsafe(do)

    async def close(self):
        self._closed = True
        self._wakeup.set()
        if self._task is not None:
            await self._task

    # ------------------------------------------------------------------
    def _admit(self, ids: List[int], max_new: int, fut: asyncio.Future,
               on_token: Optional[Any] = None,
               info: Optional[dict] = None) -> bool:
        import jax.numpy as jnp

        from seldon_core_tpu.models.transformer import PAD_POS

        free = next((i for i, s in enumerate(self._slots) if not s.active), None)
        if free is None:
            return False
        # same truncation rule as LLMServer.generate: never beyond the model's
        # trained context, and leave room for at least one generated token
        plen = min(
            _bucket(len(ids), self.len_buckets),
            self.server._cfg.max_seq_len,
            self.max_len - 1,
        )
        if len(ids) > plen:
            # same tail-keeping rule as before, but observable: batched and
            # unbatched serving can differ here (generate() sizes its cache
            # per request; the batcher's slot cache is fixed at max_len).
            # The info record travels back to the CLIENT as a response meta
            # tag / field — truncation changes outputs, so a server-side log
            # alone is not enough (ADVICE.md round 5)
            if info is not None:
                info["truncated_prompt"] = {
                    "prompt_tokens": len(ids),
                    "kept_tokens": plen,
                    "max_len": self.max_len,
                }
            logger.warning(
                "batcher truncating %d-token prompt to its last %d tokens "
                "(slot cache max_len=%d; raise continuous_batching_max_len "
                "to match generate())", len(ids), plen, self.max_len)
        if max_new > self.max_len - plen:
            logger.warning(
                "batcher will stop at %d new tokens (requested %d): slot "
                "cache max_len=%d minus prompt %d",
                self.max_len - plen, max_new, self.max_len, plen)
        ids = ids[-plen:]
        L = len(ids)
        tokens = np.zeros((1, plen), np.int32)
        positions = np.full((1, plen), PAD_POS, np.int32)
        tokens[0, :L] = ids
        positions[0, :L] = np.arange(L)

        prefill = self.server._get_prefill(1, plen, self.max_len)
        logits, cache1 = prefill(self.server._params, jnp.asarray(tokens), jnp.asarray(positions))
        self._caches = self._insert(self._caches, cache1, free)
        first_logits = np.asarray(logits[0, L - 1]).astype(np.float32)
        if float(self._temp) <= 0.0:
            first = int(first_logits.argmax())
        else:
            import jax

            self._rng, sub = jax.random.split(self._rng)
            k = min(self.server.top_k, first_logits.shape[-1])
            topi = np.argsort(first_logits)[-k:]
            draw = int(np.asarray(jax.random.categorical(
                sub, jnp.asarray(first_logits[topi]) / max(float(self._temp), 1e-6))))
            first = int(topi[draw])

        slot = self._slots[free]
        slot.active = True
        slot.future = fut
        slot.true_len = L
        slot.max_new = max_new
        slot.n_new = 1
        slot.tokens = [first]
        slot.on_token = on_token
        self._last_tok[free] = first
        self._next_pos[free] = L
        if on_token is not None and first != self.eos_id:
            on_token(first)
        if first == self.eos_id or max_new <= 1:
            self._finish(free)
        return True

    def _finish(self, i: int):
        slot = self._slots[i]
        toks = slot.tokens
        if self.eos_id in toks:
            toks = toks[: toks.index(self.eos_id)]
        if slot.on_token is not None:
            slot.on_token(None)  # stream end sentinel
        if slot.future is not None:
            self._resolve(slot.future, result=toks)
        slot.active = False
        slot.future = None
        slot.on_token = None

    def _step(self):
        import time

        import jax
        import jax.numpy as jnp

        t0 = time.perf_counter()
        self._rng, sub = jax.random.split(self._rng)
        self._caches, nxt = self._decode_step(
            self.server._params,
            self._caches,
            jnp.asarray(self._last_tok),
            jnp.asarray(self._next_pos),
            sub,
            self._temp,
        )
        nxt = np.asarray(nxt).astype(np.int32)
        # np.asarray above blocked on the device, so this wall time is the
        # real step latency; drained into the /metrics histogram at scrape
        self.server._decode_step_times.append(time.perf_counter() - t0)
        self.server._last_decode_kv_bytes = self._cache_nbytes
        for i, slot in enumerate(self._slots):
            if not slot.active:
                continue
            tok = int(nxt[i])
            slot.tokens.append(tok)
            slot.n_new += 1
            self._last_tok[i] = tok
            self._next_pos[i] += 1
            if slot.on_token is not None and tok != self.eos_id:
                slot.on_token(tok)
            if tok == self.eos_id or slot.n_new >= slot.max_new or int(self._next_pos[i]) >= self.max_len:
                self._finish(i)

    async def _run(self):
        try:
            while True:
                # admit as many pending requests as there are free slots
                # (FIFO, peek-then-pop so a failed admit keeps the request);
                # device work runs in a worker thread so the event loop (and
                # co-hosted HTTP handlers) stays responsive during decode
                while self._pending:
                    ids, max_new, fut, on_token, info = self._pending[0]
                    if not await asyncio.to_thread(self._admit, ids, max_new, fut,
                                                   on_token, info):
                        break  # no free slot — decode until one frees up
                    self._pending.popleft()
                if any(s.active for s in self._slots):
                    await asyncio.to_thread(self._step)
                    continue
                if self._closed:
                    return
                self._wakeup.clear()
                try:
                    await asyncio.wait_for(self._wakeup.wait(), timeout=0.5)
                except asyncio.TimeoutError:
                    if self._closed:
                        return
        except BaseException as e:
            # device/compile failure: fail every in-flight and queued request
            # instead of leaving their futures hanging
            logger.exception("batcher loop died: %s", e)
            for slot in self._slots:
                if slot.active:
                    if slot.on_token is not None:
                        try:
                            slot.on_token(None)  # unblock streaming consumers
                        except Exception:
                            pass
                        slot.on_token = None
                    if slot.future is not None:
                        self._resolve(slot.future, exc=e)
                    slot.active = False
                    slot.future = None
            while self._pending:
                _, _, fut, on_token, _ = self._pending.popleft()
                if on_token is not None:
                    try:
                        on_token(None)
                    except Exception:
                        pass
                self._resolve(fut, exc=e)
            raise
